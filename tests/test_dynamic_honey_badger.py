"""DynamicHoneyBadger churn tests — benchmark config 4 shape.

Reference analogs: upstream ``tests/dynamic_honey_badger.rs`` and
``tests/net_dynamic_hb.rs``: batches agree across nodes, era changes
complete (remove + add via votes and the embedded DKG), and the new
validator set signs/decrypts with the NEW threshold keys.
"""

import random


from hbbft_tpu.crypto.keys import SecretKey
from hbbft_tpu.crypto.suite import ScalarSuite
from hbbft_tpu.net import NetBuilder, ReorderingAdversary
from hbbft_tpu.protocols.dynamic_honey_badger import (
    Change,
    ChangeState,
    DhbBatch,
    DynamicHoneyBadger,
)
from hbbft_tpu.protocols.honey_badger import EncryptionSchedule


def build_dhb_net(n=4, seed=0, adversary=None, observers=0, schedule=None, f=0):
    schedule = schedule or EncryptionSchedule.always()
    b = (
        NetBuilder(n, seed=seed)
        .num_faulty(f)
        .protocol(
            lambda ni, sink, rng: DynamicHoneyBadger(
                ni, sink, session_id=b"dhb-test", encryption_schedule=schedule
            )
        )
    )
    if observers:
        b = b.observers(observers)
    if adversary is not None:
        b = b.adversary(adversary)
    return b.build()


def batches_of(net, nid):
    return [o for o in net.node(nid).outputs if isinstance(o, DhbBatch)]


def drive_epoch(net, epoch_idx, proposers=None):
    proposers = proposers if proposers is not None else net.correct_ids
    for nid in proposers:
        net.send_input(nid, [f"tx-{nid}-{epoch_idx}"])
    net.crank_until(
        lambda n: all(
            len(batches_of(n, i)) > epoch_idx for i in n.correct_ids
        ),
        max_cranks=2_000_000,
    )


def test_batches_agree_no_change():
    net = build_dhb_net(n=4, seed=3, adversary=ReorderingAdversary())
    drive_epoch(net, 0)
    drive_epoch(net, 1)
    ref = batches_of(net, 0)[:2]
    assert [b.era for b in ref] == [0, 0]
    assert all(b.change == ChangeState.none() for b in ref)
    for nid in net.correct_ids[1:]:
        assert batches_of(net, nid)[:2] == ref
    assert net.correct_faults() == []


def test_vote_remove_validator_era_change():
    net = build_dhb_net(n=4, seed=4)
    victim = 3
    new_map = {
        i: net.node(0).netinfo.public_key(i)
        for i in net.node(0).netinfo.all_ids
        if i != victim
    }
    change = Change.node_change(new_map)
    for nid in net.correct_ids:
        node = net.node(nid)
        step = node.protocol.vote_for(change, node.rng)
        net._process_step(node, step)

    epoch = 0
    max_epochs = 12
    while not all(
        any(b.change.kind == "complete" for b in batches_of(net, i))
        for i in net.correct_ids
    ):
        assert epoch < max_epochs, "era change did not complete"
        drive_epoch(net, epoch)
        epoch += 1

    # All correct nodes completed the SAME change and agree on the plan.
    plans = {}
    for nid in net.correct_ids:
        done = [b for b in batches_of(net, nid) if b.change.kind == "complete"]
        assert done[0].change.change == change
        plans[nid] = done[0].join_plan
    ref = plans[net.correct_ids[0]]
    assert all(p == ref for p in plans.values())
    assert ref.era == 1
    assert sorted(ref.validator_map()) == sorted(new_map)

    # The new era works: removed node is an observer, others validate.
    assert not net.node(victim).protocol.netinfo.is_validator()
    remaining = [i for i in net.correct_ids if i != victim]
    for nid in remaining:
        assert net.node(nid).protocol.netinfo.is_validator()
        assert net.node(nid).protocol.era == 1

    start = len(batches_of(net, remaining[0]))
    for nid in remaining:
        net.send_input(nid, [f"era1-tx-{nid}"])
    net.crank_until(
        lambda n: all(len(batches_of(n, i)) > start for i in remaining),
        max_cranks=2_000_000,
    )
    era1 = [b for b in batches_of(net, remaining[0]) if b.era == 1]
    assert era1, "no era-1 batches"
    assert net.correct_faults() == []


def test_vote_add_observer_becomes_validator():
    net = build_dhb_net(n=5, seed=5, observers=1)
    newcomer = 4
    assert not net.node(newcomer).protocol.netinfo.is_validator()
    base = net.node(0).netinfo
    new_map = {i: base.public_key(i) for i in base.all_ids}
    new_map[newcomer] = net.node(newcomer).protocol.netinfo.secret_key.public_key()
    change = Change.node_change(new_map)
    for nid in base.all_ids:
        node = net.node(nid)
        step = node.protocol.vote_for(change, node.rng)
        net._process_step(node, step)

    epoch = 0
    while not all(
        any(b.change.kind == "complete" for b in batches_of(net, i))
        for i in net.correct_ids
    ):
        assert epoch < 12, "era change did not complete"
        drive_epoch(net, epoch, proposers=list(base.all_ids))
        epoch += 1

    assert net.node(newcomer).protocol.era == 1
    assert net.node(newcomer).protocol.netinfo.is_validator()
    assert net.node(newcomer).protocol.netinfo.secret_key_share is not None

    # The promoted node proposes in era 1 and its contribution commits —
    # proof the new threshold keys (from the embedded DKG) actually work.
    start = max(len(batches_of(net, i)) for i in net.correct_ids)
    for nid in net.correct_ids:
        net.send_input(nid, [f"era1-{nid}"])
    net.crank_until(
        lambda n: any(
            b.era == 1 and newcomer in b.contribution_map()
            for i in n.correct_ids
            for b in batches_of(n, i)
        ),
        max_cranks=2_000_000,
    )
    assert net.correct_faults() == []


def test_encryption_schedule_change():
    net = build_dhb_net(n=4, seed=6)
    change = Change.encryption_schedule(EncryptionSchedule.never())
    for nid in net.correct_ids:
        node = net.node(nid)
        net._process_step(node, node.protocol.vote_for(change, node.rng))
    drive_epoch(net, 0)
    done = [b for b in batches_of(net, 0) if b.change.kind == "complete"]
    assert done and done[0].change.change == change
    assert net.node(0).protocol.era == 1
    assert net.node(0).protocol.encryption_schedule == EncryptionSchedule.never()
    assert net.correct_faults() == []


def test_join_plan_construction():
    """from_join_plan yields an observer aligned with the plan's era."""
    suite = ScalarSuite()
    net = build_dhb_net(n=4, seed=7)
    victim = 3
    new_map = {
        i: net.node(0).netinfo.public_key(i)
        for i in net.node(0).netinfo.all_ids
        if i != victim
    }
    for nid in net.correct_ids:
        node = net.node(nid)
        net._process_step(
            node, node.protocol.vote_for(Change.node_change(new_map), node.rng)
        )
    epoch = 0
    while not any(b.change.kind == "complete" for b in batches_of(net, 0)):
        assert epoch < 12
        drive_epoch(net, epoch)
        epoch += 1
    plan = [b for b in batches_of(net, 0) if b.change.kind == "complete"][0].join_plan
    sk = SecretKey.random(random.Random(99), suite)
    from hbbft_tpu.crypto.pool import VerifyPool

    joiner = DynamicHoneyBadger.from_join_plan(
        "joiner", sk, plan, VerifyPool(), session_id=b"dhb-test"
    )
    assert joiner.era == plan.era
    assert not joiner.netinfo.is_validator()
    assert sorted(joiner.netinfo.all_ids) == sorted(plan.validator_map())
