"""ThresholdSign over the VirtualNet — benchmark config 1 shape (4-of-7).

Reference test analog: upstream ``tests/threshold_sign.rs`` — all correct
nodes terminate with the identical valid signature and empty fault logs.
"""

import pytest

from hbbft_tpu.crypto.keys import SignatureShare
from hbbft_tpu.net import NetBuilder, NullAdversary, RandomAdversary, ReorderingAdversary
from hbbft_tpu.net.virtual_net import NetMessage
from hbbft_tpu.protocols.threshold_sign import SignMessage, ThresholdSign

DOC = b"sign me: epoch 0 coin"


def build_net(n=7, seed=0, adversary=None, flush_every=1):
    b = (
        NetBuilder(n, seed=seed)
        .protocol(lambda ni, sink, rng: ThresholdSign(ni, DOC, sink))
        .flush_every(flush_every)
    )
    if adversary is not None:
        b = b.adversary(adversary)
    return b.build()


@pytest.mark.parametrize("adversary", [NullAdversary(), ReorderingAdversary()])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_all_nodes_agree_on_signature(adversary, seed):
    net = build_net(seed=seed, adversary=adversary)
    net.broadcast_input(lambda nid: None)
    net.run_to_termination()
    outs = net.outputs()
    sigs = {nid: o for nid, (o,) in ((k, v) for k, v in outs.items())}
    first = next(iter(sigs.values()))
    assert all(s.g2 == first.g2 for s in sigs.values())
    pks = net.node(0).netinfo.public_key_set
    assert pks.verify_signature(DOC, first)
    assert net.correct_faults() == []


def test_batched_flush_policy_same_result():
    net_eager = build_net(seed=42, flush_every=1)
    net_batch = build_net(seed=42, flush_every=8)
    for net in (net_eager, net_batch):
        net.broadcast_input(lambda nid: None)
        net.run_to_termination()
    sig_a = net_eager.node(0).outputs[0]
    sig_b = net_batch.node(0).outputs[0]
    assert sig_a.g2 == sig_b.g2


def test_invalid_share_is_faulted():
    net = build_net(n=7)
    # Inject a garbage share "from" faulty node 6 to node 0 ahead of all
    # honest traffic, so it is verified before node 0 can terminate.
    suite = net.node(0).netinfo.public_key_set.suite
    bogus = SignatureShare(suite.hash_to_g2(b"garbage"), suite)
    net.inject(NetMessage(sender=6, dest=0, payload=SignMessage(bogus)))
    net.broadcast_input(lambda nid: None)
    net.run_to_termination()
    faults = [f for f in net.node(0).faults if f.node_id == 6]
    assert any("invalid-share" in f.kind for f in faults)
    # Consensus still completed despite the bad share.
    assert net.node(0).outputs


def test_observer_numbers():
    # 10 nodes, f = 3: termination requires only f+1 = 4 shares; drop all
    # messages from 3 (crash-)faulty nodes and ensure liveness.
    net = build_net(n=10)
    assert len(net.faulty_ids) == 3
    net.broadcast_input(lambda nid: None)
    net.run_to_termination()
    for nid in net.correct_ids:
        assert len(net.node(nid).outputs) == 1


def test_random_adversary_replay_does_not_break(monkeypatch):
    net = build_net(n=7, seed=9, adversary=RandomAdversary(replay_p=0.5))
    net.broadcast_input(lambda nid: None)
    net.run_to_termination()
    for nid in net.correct_ids:
        assert len(net.node(nid).outputs) == 1


def test_coin_fairness_statistics():
    """Upstream threshold_sign tests include coin-fairness statistics:
    the combined signature's parity over many distinct round nonces must
    be roughly balanced (it seeds the ABA common coin)."""
    import random

    from hbbft_tpu.crypto.keys import SecretKeySet
    from hbbft_tpu.crypto.suite import ScalarSuite

    suite = ScalarSuite()
    rng = random.Random(99)
    sks = SecretKeySet.random(2, rng, suite)
    pks = sks.public_keys()
    trials = 400
    ones = 0
    for r in range(trials):
        doc = b"coin-%d" % r
        shares = {i: sks.secret_key_share(i).sign(doc) for i in range(3)}
        sig = pks.combine_signatures(shares)
        assert pks.verify_signature(doc, sig)
        ones += int(sig.parity())
    # 400 fair flips: P(|ones-200| > 60) < 1e-8.
    assert abs(ones - trials / 2) <= 60, f"biased coin: {ones}/{trials}"
