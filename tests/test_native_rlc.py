"""Scalar RLC batch verification (round 7): byte-identity matrix.

The native engine's deferred RLC path groups COIN/DECRYPT share checks
per Ts/Td instance and verifies each group with one random-linear-
combination check (``scalar_rlc_verdicts``), bisecting failed groups so
every bad share is attributed exactly like the per-share path.  The
invariant pinned here (docs/INVARIANTS.md "RLC byte-identity"):

* ``flush_every=1`` keeps the pre-round-7 flush points, so RLC on/off
  is byte-identical — batch sequences AND exact fault-log sequences.
* ``flush_every=0`` (queue-dry deferral, maximal grouping) reorders
  WORK, never results: batch sequences stay identical and fault logs
  match as multisets (deferral can permute the order faults land in a
  node's log, exactly like the ext-mode flush_every invariant).
* Both hold under an adversary submitting corrupt coin and decryption
  shares (the bisection path), with every fault pinned on a tampered
  sender.
"""

import ctypes

import pytest

from hbbft_tpu import native_engine
from hbbft_tpu.net import NetBuilder
from hbbft_tpu.net.adversary import TamperingAdversary
from hbbft_tpu.protocols.dynamic_honey_badger import DhbBatch
from hbbft_tpu.protocols.queueing_honey_badger import Input, QueueingHoneyBadger

pytestmark = pytest.mark.skipif(
    not native_engine.available(), reason="native engine unavailable"
)

SESSION = b"rlc-test"

TS_INVALID = "threshold_sign:invalid-share"
TD_INVALID = "threshold_decrypt:invalid-share"


# Engine MsgType values for BA_COIN / HB_DECRYPT (native/engine.cpp).
MT_COIN, MT_DECRYPT = 8, 10


def noncanonical_node0_shares(nat):
    """Node 0 re-encodes every outgoing share as ``value + r`` (still 32
    bytes: r is ~254.9 bits) — CONGRUENT to the honest share but not
    canonical.  The per-share TS check is representational equality and
    faults it; the per-share TD check routes the share through mulmod
    on both sides and accepts it.  The RLC group path must reproduce
    exactly that asymmetry (a congruence-only group check would accept
    the TS share and silently diverge the fault logs — the round-7
    review's counterexample)."""
    lib, h = nat.lib, nat.handle
    mod = nat._suite.scalar_modulus

    def on_tamper(sender, mtype, era, epoch, proposer, rnd):
        if mtype not in (MT_COIN, MT_DECRYPT):
            return
        buf = (ctypes.c_uint8 * 32)()
        lib.hbe_tamper_share(h, buf)
        s = int.from_bytes(bytes(buf), "big")
        out = (s + mod).to_bytes(32, "big")  # s < r and r < 2^255: fits
        ob = (ctypes.c_uint8 * 32).from_buffer_copy(out)
        lib.hbe_tamper_set_share(h, ob, 32)

    nat._corrupt_cb = native_engine._TAMPER_CB(on_tamper)  # keep alive
    lib.hbe_set_tamper(h, nat._corrupt_cb)
    lib.hbe_set_tampered(h, 0, 1)


def corrupt_node0_shares(nat):
    """Make node 0 Byzantine in a content-deterministic way: a raw
    engine tamper callback doubles every outgoing COIN/DECRYPT share of
    node 0 and touches nothing else.

    Why not the stock TamperingAdversary for the decrypt side: its
    faulty nodes sort LAST in the FIFO delivery order, so their corrupt
    decryption shares systematically arrive after f+1 honest shares
    terminated the instance — dropped without ever reaching a verdict
    (no fault, nothing for the RLC bisection to find).  Node 0 is FIRST
    in every broadcast fan-out, so its corrupt shares reach verdicts
    before termination.  And because the corruption depends only on the
    message content (no rng, no schedule), runs at different flush
    cadences see the SAME corruption — which is what makes the
    RLC-on/off × flush_every matrix comparable under attack."""
    lib, h = nat.lib, nat.handle
    mod = nat._suite.scalar_modulus

    def on_tamper(sender, mtype, era, epoch, proposer, rnd):
        if mtype not in (MT_COIN, MT_DECRYPT):
            return
        buf = (ctypes.c_uint8 * 32)()
        lib.hbe_tamper_share(h, buf)
        s = int.from_bytes(bytes(buf), "big")
        out = (2 * s % mod).to_bytes(32, "big")
        ob = (ctypes.c_uint8 * 32).from_buffer_copy(out)
        lib.hbe_tamper_set_share(h, ob, 32)

    nat._corrupt_cb = native_engine._TAMPER_CB(on_tamper)  # keep alive
    lib.hbe_set_tamper(h, nat._corrupt_cb)
    lib.hbe_set_tampered(h, 0, 1)


def run_native(n, seed, *, epochs=2, num_faulty=None, adversary=None,
               corrupt_node0=False, noncanonical_node0=False, **kw):
    nat = native_engine.NativeQhbNet(
        n, seed=seed, batch_size=8, num_faulty=num_faulty,
        session_id=SESSION, adversary=adversary, **kw,
    )
    if corrupt_node0:
        corrupt_node0_shares(nat)
    if noncanonical_node0:
        noncanonical_node0_shares(nat)
    for k in range(epochs):
        for nid in nat.correct_ids:
            nat.send_input(nid, Input.user(f"tx-{k}-{nid}"))
    nat.run_until(
        lambda e: all(
            len(e.nodes[i].outputs) >= epochs for i in e.correct_ids
        ),
        chunk=5000,
    )
    out = {
        "batches": [
            [
                (b.era, b.epoch, b.contributions, b.change, b.join_plan)
                for b in nat.nodes[i].outputs
            ]
            for i in nat.correct_ids
        ],
        "faults": [nat.faults(i) for i in nat.correct_ids],
        "prof": nat.prof_stats(),
        "faulty_ids": list(nat.faulty_ids),
    }
    nat.close()
    return out


def test_rlc_on_off_byte_identical_at_flush_every_1():
    """RLC on, flush_every=1: the grouped verdicts ride the exact
    pre-round-7 flush points — everything byte-identical, fault ORDER
    included."""
    n, seed = 16, 7
    old = run_native(n, seed, rlc=False)
    new = run_native(n, seed, rlc=True, flush_every=1)
    assert new["batches"] == old["batches"]
    assert new["faults"] == old["faults"]


def test_rlc_deferred_output_identical_at_flush_every_0():
    """Queue-dry deferral (maximal grouping + folded group
    continuations): identical batch sequences, fault multisets — and the
    profile must prove grouping actually happened (a silently-eager RLC
    path would pass the equality checks trivially)."""
    n, seed = 16, 7
    old = run_native(n, seed, rlc=False)
    new = run_native(n, seed, rlc=True, flush_every=0)
    assert new["batches"] == old["batches"]
    assert [sorted(f) for f in new["faults"]] == [
        sorted(f) for f in old["faults"]
    ]
    groups = new["prof"]["rlc_groups"]["count"]
    shares = (
        new["prof"]["COIN"]["count"] + new["prof"]["DECRYPT"]["count"]
    )
    assert groups > 0
    # multi-share groups exist: strictly fewer groups than shares
    assert groups < shares
    assert old["prof"]["rlc_groups"]["count"] == 0


def test_rlc_deferred_with_silent_faulty():
    n, seed, f = 16, 11, 5
    old = run_native(n, seed, num_faulty=f, rlc=False)
    new = run_native(n, seed, num_faulty=f, rlc=True, flush_every=0)
    assert new["batches"] == old["batches"]
    assert [sorted(x) for x in new["faults"]] == [
        sorted(x) for x in old["faults"]
    ]


@pytest.mark.parametrize("flush_every", [2, 7])
def test_rlc_deferred_matches_python_net_cadence(flush_every):
    """The scalar deferred cadence mirrors VirtualNet's flush_every
    machinery (count per delivered message / top-level input, sorted
    dirty-node rounds, queue-dry drain): at the same seed and cadence
    the engine commits the same batch sequence as the pure-Python
    stack.  Fault logs compare as multisets — the folded group
    continuations may permute fault positions within one flush."""
    n, seed = 6, 13
    pynet = (
        NetBuilder(n, seed=seed)
        .num_faulty(1)
        .max_cranks(10_000_000)
        .flush_every(flush_every)
        .protocol(
            lambda ni, sink, rng: QueueingHoneyBadger(
                ni, sink, batch_size=8, session_id=SESSION
            )
        )
        .build()
    )
    nat = native_engine.NativeQhbNet(
        n, seed=seed, batch_size=8, num_faulty=1, session_id=SESSION,
        rlc=True, flush_every=flush_every,
    )
    for k in range(2):
        for nid in nat.correct_ids:
            pynet.send_input(nid, Input.user(f"tx-{k}-{nid}"))
            nat.send_input(nid, Input.user(f"tx-{k}-{nid}"))

    def py_batches(nid):
        return [
            o for o in pynet.node(nid).outputs if isinstance(o, DhbBatch)
        ]

    pynet.crank_until(
        lambda net: all(
            len(py_batches(i)) >= 2 for i in net.correct_ids
        ),
        max_cranks=10_000_000,
    )
    nat.run_until(
        lambda e: all(len(e.nodes[i].outputs) >= 2 for i in e.correct_ids),
        chunk=1,
    )
    for nid in pynet.correct_ids:
        pyb = [
            (b.era, b.epoch, b.contributions, b.change, b.join_plan)
            for b in py_batches(nid)
        ]
        nab = [
            (b.era, b.epoch, b.contributions, b.change, b.join_plan)
            for b in nat.nodes[nid].outputs
        ]
        assert pyb == nab, f"node {nid} diverged"
        pyf = sorted((fl.node_id, fl.kind) for fl in pynet.node(nid).faults)
        naf = sorted(nat.faults(nid))
        assert pyf == naf, f"node {nid} fault multisets diverged"
    nat.close()


def test_rlc_stock_tampering_adversary_byte_identical():
    """The full stock TamperingAdversary rewrite set (flipped bvals,
    corrupt proofs/roots, doubled shares) at flush_every=1: RLC on/off
    must agree byte-for-byte — outputs AND exact fault logs.  (Its
    corrupt DECRYPT shares systematically arrive post-termination on
    the FIFO net and are dropped verdict-less; the corrupt-node0
    harness below covers the decrypt bisection.)"""
    n, seed = 16, 5
    old = run_native(
        n, seed, rlc=False, adversary=TamperingAdversary(tamper_p=1.0)
    )
    new = run_native(
        n, seed, rlc=True, flush_every=1,
        adversary=TamperingAdversary(tamper_p=1.0),
    )
    assert new["batches"] == old["batches"]
    assert new["faults"] == old["faults"]
    kinds = {k for flog in new["faults"] for (_, k) in flog}
    assert TS_INVALID in kinds, "no corrupt coin share reached a verdict"


def test_rlc_corrupt_shares_matrix():
    """Corrupt coin AND decryption shares from node 0 (deterministic
    content-only tampering — corrupt_node0_shares notes) across the
    whole matrix: RLC off / on×flush_every=1 byte-identical (exact
    fault order — pins the bisection's exact attribution), on×0
    output-identical with matching fault multisets, every invalid-share
    fault naming node 0, both fault kinds present, and failed groups
    really flowing through the deferred grouping."""
    n, seed = 16, 5
    old = run_native(n, seed, rlc=False, corrupt_node0=True)
    fe1 = run_native(n, seed, rlc=True, flush_every=1, corrupt_node0=True)
    fe0 = run_native(n, seed, rlc=True, flush_every=0, corrupt_node0=True)
    assert fe1["batches"] == old["batches"]
    assert fe1["faults"] == old["faults"]
    assert fe0["batches"] == old["batches"]
    assert [sorted(f) for f in fe0["faults"]] == [
        sorted(f) for f in old["faults"]
    ]
    for arm in (old, fe1, fe0):
        kinds = {k for flog in arm["faults"] for (_, k) in flog}
        assert TS_INVALID in kinds, "no corrupt coin share reached a verdict"
        assert TD_INVALID in kinds, (
            "no corrupt decryption share reached a verdict"
        )
        for flog in arm["faults"]:
            for subj, kind in flog:
                if kind in (TS_INVALID, TD_INVALID):
                    assert subj == 0
    assert fe0["prof"]["rlc_groups"]["count"] > 0
    # determinism of the deferred adversarial run (the bisection path)
    again = run_native(n, seed, rlc=True, flush_every=0, corrupt_node0=True)
    assert again["batches"] == fe0["batches"]
    assert again["faults"] == fe0["faults"]


def test_rlc_noncanonical_share_encodings_match_per_share_path():
    """Shares re-encoded as value+r (congruent, non-canonical): the
    per-share TS check is representational and faults them, the
    per-share TD check is congruence and accepts them — the RLC path
    must mirror BOTH behaviors exactly across the matrix."""
    n, seed = 16, 5
    old = run_native(n, seed, rlc=False, noncanonical_node0=True)
    fe1 = run_native(n, seed, rlc=True, flush_every=1,
                     noncanonical_node0=True)
    fe0 = run_native(n, seed, rlc=True, flush_every=0,
                     noncanonical_node0=True)
    assert fe1["batches"] == old["batches"]
    assert fe1["faults"] == old["faults"]
    assert fe0["batches"] == old["batches"]
    assert [sorted(f) for f in fe0["faults"]] == [
        sorted(f) for f in old["faults"]
    ]
    for arm in (old, fe1, fe0):
        kinds = {k for flog in arm["faults"] for (_, k) in flog}
        # TS: representational -> faulted in every arm.
        assert TS_INVALID in kinds
        # TD: congruence both paths -> never faulted in any arm.
        assert TD_INVALID not in kinds


def test_rlc_deferred_typed_profile_attribution():
    """Deferred flushes run outside the typed delivery stamp; the engine
    must fold verification + continuation cycles back into the
    COIN/DECRYPT slots (otherwise the HBBFT_TPU_COIN_RLC A/B would
    compare a number that silently excludes the RLC arm's own work)."""
    out = run_native(16, 7, rlc=True, flush_every=0)
    prof = out["prof"]
    assert prof["COIN"]["count"] > 0
    assert prof["COIN"]["cycles"] > 0
    assert prof["DECRYPT"]["cycles"] > 0
    assert prof["rlc_groups"]["cycles"] > 0


def test_scalar_flush_every_requires_rlc():
    with pytest.raises(ValueError):
        native_engine.NativeQhbNet(4, seed=1, rlc=False, flush_every=0)


def test_threads_reject_deferred_scalar_cadence():
    with pytest.raises(ValueError):
        native_engine.NativeQhbNet(
            4, seed=1, rlc=True, flush_every=0, threads=2
        )
