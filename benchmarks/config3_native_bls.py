"""BASELINE config 3 on the FUSED stack: native C++ message loop + real
BLS12-381 crypto plane + deferred-verify flush (round-3 VERDICT item #1).

N=16 QueueingHoneyBadger, 256 transactions, real threshold crypto: the
engine runs the whole network's message loop natively; signing /
combining / serde gates call back per instance; verifications accumulate
in the engine pools and flush through the configured CryptoBackend when
the delivery queue runs dry (``flush_every=0`` — maximal amortization).

Prints one JSON line per epoch batch committed plus a summary line.

Env knobs: BENCH_NODES (16), BENCH_TXNS (256), BENCH_BATCH (256),
BENCH_BACKEND (batched|eager|tpu|hybrid), BENCH_FLUSH (0).
"""

from __future__ import annotations

import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hbbft_tpu import native_engine
from hbbft_tpu.crypto.backend import BatchedBackend, EagerBackend
from hbbft_tpu.crypto.bls import BLSSuite
from hbbft_tpu.protocols.queueing_honey_badger import Input


def make_backend(name: str, suite):
    if name == "eager":
        return EagerBackend(suite)
    if name == "tpu":
        from hbbft_tpu.crypto.tpu import TpuBackend

        return TpuBackend(suite)
    if name == "hybrid":
        # The deployment-shaped choice: flushes below min_device_batch
        # ride the host (this config's mean flush is ~4 requests — a
        # device round-trip per tiny flush, plus a fresh ~10-min compile
        # per small shape bucket, would swamp the epoch); the big deduped
        # flushes ride the chip.  Failover scope: HybridBackend handles a
        # device that is absent at CONSTRUCTION or dies MID-RUN — but
        # importing jax at all hangs when the axon relay is down
        # (CLAUDE.md gotcha), so on a dead relay run this with
        # JAX_PLATFORMS=cpu (the battery only selects hybrid after its
        # TPU probe succeeds).
        from hbbft_tpu.crypto.tpu import HybridBackend

        return HybridBackend(suite, min_device_batch=64)
    return BatchedBackend(suite)


def main() -> None:
    n = int(os.environ.get("BENCH_NODES", "16"))
    n_txns = int(os.environ.get("BENCH_TXNS", "256"))
    batch_size = int(os.environ.get("BENCH_BATCH", "256"))
    backend_name = os.environ.get("BENCH_BACKEND", "batched")
    flush_every = int(os.environ.get("BENCH_FLUSH", "0"))
    suite = BLSSuite()

    t0 = time.perf_counter()
    nat = native_engine.NativeQhbNet(
        n,
        seed=0,
        batch_size=batch_size,
        num_faulty=0,  # all-correct: every node proposes (sim config-3 shape)
        session_id=b"config3-bls",
        suite=suite,
        backend=make_backend(backend_name, suite),
        flush_every=flush_every,
    )
    setup_s = time.perf_counter() - t0

    rng = random.Random(7)
    txns = [rng.randbytes(16) for _ in range(n_txns)]
    t0 = time.perf_counter()
    for i, txn in enumerate(txns):
        nat.send_input(i % n, Input.user(txn))
    want = set(txns)

    def committed(nid: int) -> set:
        return {
            t
            for b in nat.nodes[nid].outputs
            for _, c in b.contributions
            if isinstance(c, (list, tuple))
            for t in c
        }

    epoch_walls = []
    last = time.perf_counter()
    while not all(want <= committed(i) for i in nat.correct_ids):
        prev_batches = len(nat.nodes[0].outputs)
        nat.run_until(
            lambda e, w=prev_batches + 1: all(
                len(e.nodes[i].outputs) >= w for i in e.correct_ids
            ),
            chunk=5000,
        )
        now = time.perf_counter()
        epoch_walls.append(now - last)
        last = now
        b = nat.nodes[0].outputs[-1]
        print(
            json.dumps(
                {
                    "epoch": b.epoch,
                    "wall_s": round(epoch_walls[-1], 2),
                    "txs_committed": len(committed(0) & want),
                    "delivered": nat.delivered,
                }
            ),
            flush=True,
        )
    total = time.perf_counter() - t0

    st = nat.flush_stats
    print(
        json.dumps(
            {
                "config": "config3_native_bls",
                "nodes": n,
                "suite": "bls12-381",
                "backend": backend_name,
                "flush_every": flush_every,
                "txns": n_txns,
                "epochs": len(epoch_walls),
                "epoch_latency_s": round(total / max(1, len(epoch_walls)), 2),
                "total_wall_s": round(total, 2),
                "setup_s": round(setup_s, 2),
                "delivered": nat.delivered,
                "msgs_per_s": round(nat.delivered / total, 1),
                "verify_flushes": st["flushes"],
                "verify_requests": st["requests"],
                "backend_requests": st["backend_requests"],
                "max_flush_batch": st["max_batch"],
                "reqs_per_flush": round(
                    st["backend_requests"] / max(1, st["flushes"]), 2
                ),
            }
        )
    )
    nat.close()


if __name__ == "__main__":
    main()
