"""BASELINE config 5: the 10k-validator BLS share-verify firehose.

The north-star scale (BASELINE.json:11): accumulate an epoch's worth of
signature shares at 10k-validator scale and verify them as one batched
flush on the accelerator.  Prints one JSON line.

On a machine without the TPU this still runs (CPU XLA) but the number is
meaningless; the driver's ``bench.py`` run on real hardware is the
recorded headline.  ``BENCH_SHARES`` scales the batch (default 10240 ~
"10k validators' coin shares in one epoch").
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hbbft_tpu.utils.jaxcache import enable_cache

enable_cache()

import random

from hbbft_tpu.crypto.backend import VerifyRequest
from hbbft_tpu.crypto.bls.suite import BLSSuite
from hbbft_tpu.crypto.keys import SecretKeySet
from hbbft_tpu.crypto.tpu.backend import TpuBackend


def main() -> None:
    n_shares = int(os.environ.get("BENCH_SHARES", "10240"))
    suite = BLSSuite()
    rng = random.Random(13)
    # Key material for a handful of signer indices; the batch reuses
    # them round-robin (verification cost is per share, not per signer).
    sks = SecretKeySet.random(3, rng, suite)
    pks = sks.public_keys()
    msg = b"firehose epoch document"
    shares = [sks.secret_key_share(i % 10).sign(msg) for i in range(10)]
    reqs = [
        VerifyRequest.sig_share(pks.public_key_share(i % 10), msg, shares[i % 10])
        for i in range(n_shares)
    ]

    backend = TpuBackend(suite)
    t0 = time.perf_counter()
    warm = backend.verify_batch(reqs)
    compile_s = time.perf_counter() - t0
    assert all(warm)

    t0 = time.perf_counter()
    res = backend.verify_batch(reqs)
    dt = time.perf_counter() - t0
    assert all(res)

    import jax

    print(
        json.dumps(
            {
                "config": "firehose_10k_share_verify",
                "shares": n_shares,
                "verifies_per_sec": round(n_shares / dt, 1),
                "flush_latency_s": round(dt, 4),
                "north_star_under_50ms": dt < 0.05,
                "first_call_s": round(compile_s, 1),
                "backend": jax.default_backend(),
            }
        )
    )


if __name__ == "__main__":
    main()
