"""BASELINE config 4: DynamicHoneyBadger 64-node with validator churn.

Runs a 64-node virtual net of QueueingHoneyBadger (DynamicHoneyBadger +
transaction queue — the queue re-proposes every epoch, which is what
keeps Subset fed while the embedded SyncKeyGen's Part/Ack messages ride
through consensus), commits a plain epoch, votes a validator out, and
measures wall time to the completed era change.  Scalar suite — this
measures the protocol/DKG control plane, the part that is CPU-bound
regardless of crypto backend.  One JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hbbft_tpu.net import NetBuilder
from hbbft_tpu.protocols.dynamic_honey_badger import Change, DhbBatch
from hbbft_tpu.protocols.queueing_honey_badger import Input, QueueingHoneyBadger


def batches_of(net, nid):
    return [o for o in net.node(nid).outputs if isinstance(o, DhbBatch)]


def main() -> None:
    if os.environ.get("BENCH_NATIVE"):
        return main_native()
    n = int(os.environ.get("BENCH_NODES", "64"))
    t0 = time.perf_counter()
    net = (
        NetBuilder(n, seed=4)
        .num_faulty(0)
        .max_cranks(100_000_000)
        .protocol(
            lambda ni, sink, rng: QueueingHoneyBadger(
                ni, sink, batch_size=n, session_id=b"cfg4"
            )
        )
        .build()
    )
    setup_s = time.perf_counter() - t0

    # Phase 1: a plain epoch.
    t0 = time.perf_counter()
    for nid in net.correct_ids:
        net.send_input(nid, Input.user(f"pre-{nid}"))
    net.crank_until(
        lambda net_: all(batches_of(net_, i) for i in net_.correct_ids),
        max_cranks=50_000_000,
    )
    epoch_s = time.perf_counter() - t0
    epochs_before = max(len(batches_of(net, i)) for i in net.correct_ids)

    # Phase 2: vote a validator out -> era change (DKG among the rest).
    victim = n - 1
    ni = net.node(0).protocol.netinfo
    new_map = {i: ni.public_key(i) for i in ni.all_ids if i != victim}
    t0 = time.perf_counter()
    for nid in net.correct_ids:
        net.send_input(nid, Input.change(Change.node_change(new_map)))
        net.send_input(nid, Input.user(f"churn-{nid}"))
    net.crank_until(
        lambda net_: all(
            any(b.change.kind == "complete" for b in batches_of(net_, i))
            for i in net_.correct_ids
        ),
        max_cranks=50_000_000,
    )
    churn_s = time.perf_counter() - t0
    epochs_after = max(len(batches_of(net, i)) for i in net.correct_ids)
    assert not net.node(victim).protocol.netinfo.is_validator()

    print(
        json.dumps(
            {
                "config": "dynamic_hb_64node_churn",
                "nodes": n,
                "keygen_setup_s": round(setup_s, 2),
                "plain_epoch_wall_s": round(epoch_s, 2),
                "era_change_wall_s": round(churn_s, 2),
                "epochs_to_complete_change": epochs_after - epochs_before,
                "delivered_msgs": net.delivered,
            }
        )
    )


def main_native() -> None:
    """Same phases on the native C++ protocol engine (BENCH_NATIVE=1).

    The engine is output-equivalent to the Python stack at the same seed
    (tests/test_native_engine.py); this measures the native message loop
    with the Python DHB/QHB batch layers on top."""
    from hbbft_tpu import native_engine

    n = int(os.environ.get("BENCH_NODES", "64"))
    chunk = int(os.environ.get("BENCH_CHUNK", "20000"))
    t0 = time.perf_counter()
    nat = native_engine.NativeQhbNet(
        n, seed=4, batch_size=n, num_faulty=0, session_id=b"cfg4"
    )
    setup_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    for nid in nat.correct_ids:
        nat.send_input(nid, Input.user(f"pre-{nid}"))
    nat.run_until(
        lambda e: all(len(e.nodes[i].outputs) >= 1 for i in e.correct_ids),
        chunk=chunk,
    )
    epoch_s = time.perf_counter() - t0
    epochs_before = max(len(nat.nodes[i].outputs) for i in nat.correct_ids)

    victim = n - 1
    ni = nat.nodes[0].qhb.dhb.netinfo
    new_map = {i: ni.public_key(i) for i in ni.all_ids if i != victim}
    t0 = time.perf_counter()
    for nid in nat.correct_ids:
        nat.send_input(nid, Input.change(Change.node_change(new_map)))
        nat.send_input(nid, Input.user(f"churn-{nid}"))
    nat.run_until(
        lambda e: all(
            any(b.change.kind == "complete" for b in e.nodes[i].outputs)
            for i in e.correct_ids
        ),
        chunk=chunk,
    )
    churn_s = time.perf_counter() - t0
    epochs_after = max(len(nat.nodes[i].outputs) for i in nat.correct_ids)
    assert not nat.nodes[victim].qhb.dhb.netinfo.is_validator()

    record = {
        "config": "dynamic_hb_64node_churn",
        "engine": "native",
        "nodes": n,
        "keygen_setup_s": round(setup_s, 2),
        "plain_epoch_wall_s": round(epoch_s, 2),
        "era_change_wall_s": round(churn_s, 2),
        "epochs_to_complete_change": epochs_after - epochs_before,
        "delivered_msgs": nat.delivered,
    }
    if os.environ.get("BENCH_PROF"):
        # Era-change split in Gcyc (hbe_prof_cycles — the A/B currency
        # per the clock-drift rule in CLAUDE.md), slots per
        # tools/lint/slot_registry.py: 11 = RLC group stats, 12 = Python
        # batch_cb wall (the round-6 batch-digest split; its slot-15
        # contrib_cb partner retired in round 17), 13 = epoch-advance
        # wall, 14 = the SIMD combine-kernel wall (round 15; the old
        # round-4 continuation-split names died with their slots —
        # don't compare against round-4/5 numbers).  Slot 15 is the
        # arena stats now (cycles = max per-node high-water mark BYTES,
        # not cycles) — exported via arena_stats()/sha3_stats below,
        # not the Gcyc loop.
        lib, h = nat.lib, nat.handle
        prof = {}
        for slot, name in (
            (14, "combine_kernel"), (13, "epoch_advance"), (11, "rlc_groups"),
            (12, "batch_cb"),
        ):
            prof[name + "_gcyc"] = round(
                int(lib.hbe_prof_cycles(h, slot)) / 1e9, 3
            )
            prof[name + "_n"] = int(lib.hbe_prof_count(h, slot))
        record["prof"] = prof
        record["arena"] = nat.arena_stats()
        record["sha3"] = nat.sha3_stats()
        record["dkg_batch"] = os.environ.get("HBBFT_TPU_DKG_BATCH", "1")
    print(json.dumps(record))


if __name__ == "__main__":
    main()
