"""Flush-kernel roofline: stage walls + XLA cost analysis (round-5 #1).

The round-3/4 verdicts asked what fraction of the chip the flush
actually uses — without it, "how much headroom remains" is a guess.
This measures, on a WARM cache:

* scan-stage wall (RLC scalar-mul scans + subgroup chains + tree sums)
  and pair-stage wall (batched Miller + final exp) separately, via the
  round-5 two-stage split,
* end-to-end ``verify_batch`` wall at the same size,
* XLA's own ``cost_analysis`` (flops / bytes accessed) for both
  compiled kernels, from which flops/s and the roofline position are
  derived in BASELINE.md.

One JSON line.  ``ROOFLINE_SHARES`` (default 2048) sets the batch; the
shapes must already be cached or this pays their one-time compile.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hbbft_tpu.utils.jaxcache import enable_cache

enable_cache()

import random  # noqa: E402

import jax  # noqa: E402

from hbbft_tpu.crypto.backend import VerifyRequest  # noqa: E402
from hbbft_tpu.crypto.bls.suite import BLSSuite  # noqa: E402
from hbbft_tpu.crypto.keys import SecretKeySet  # noqa: E402
from hbbft_tpu.crypto.tpu import backend as tb  # noqa: E402


def _block(tree) -> None:
    jax.block_until_ready(tree)


def _relay_backed_tpu() -> bool:
    """True on the axon relay-backed TPU platform (CLAUDE.md env
    gotchas): the one real chip is registered through a local relay by
    the axon plugin, which pins JAX_PLATFORMS=axon.  The AOT
    ``.lower().compile()`` path that cost_analysis needs bypasses the
    persistent-cache fast path there and WEDGED a round-5 battery step
    at 2700 s — so the cost stage defaults OFF on that platform."""
    if "axon" in (os.environ.get("JAX_PLATFORMS") or ""):
        return True
    try:
        return any(
            getattr(d, "platform", "") in ("axon", "tpu") for d in jax.devices()
        )
    except Exception:  # pragma: no cover - backend init failure
        return False


def _skip_cost() -> Optional[str]:
    """Reason to skip the cost_analysis stage, or None to run it.
    ROOFLINE_SKIP_COST stays the explicit override in both directions:
    "1" forces the skip anywhere, "0" forces the stage even on the
    relay platform."""
    env = os.environ.get("ROOFLINE_SKIP_COST")
    if env is not None:
        return "ROOFLINE_SKIP_COST=1" if env not in ("", "0") else None
    if _relay_backed_tpu():
        return (
            "relay-backed TPU platform (lower+compile wedged at 2700 s "
            "round 5; set ROOFLINE_SKIP_COST=0 to force)"
        )
    return None


def _cost(fn, *args) -> dict:
    """flops / bytes-accessed estimates from the compiled executable."""
    try:
        compiled = fn.lower(*args).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        keep = {}
        for k in ("flops", "bytes accessed", "transcendentals"):
            if k in ca:
                keep[k.replace(" ", "_")] = float(ca[k])
        return keep
    except Exception as e:  # pragma: no cover - platform-dependent API
        return {"error": f"{type(e).__name__}: {e}"[:160]}


def main() -> None:
    n_shares = int(os.environ.get("ROOFLINE_SHARES", "2048"))
    reps = int(os.environ.get("ROOFLINE_REPS", "3"))
    suite = BLSSuite()
    rng = random.Random(7)
    sks = SecretKeySet.random(2, rng, suite)
    pks = sks.public_keys()
    msg = b"hbbft-tpu benchmark epoch document"
    backend = tb.TpuBackend(suite)
    shares8 = [sks.secret_key_share(k).sign(msg) for k in range(8)]
    reqs = [
        VerifyRequest.sig_share(pks.public_key_share(i % 8), msg, shares8[i % 8])
        for i in range(n_shares)
    ]

    # Warm + correctness (compiles scan + pair buckets if cold).
    t0 = time.perf_counter()
    assert all(backend.verify_batch(reqs)), "warmup verification failed"
    warm_s = time.perf_counter() - t0

    # End-to-end.
    e2e = []
    for _ in range(reps):
        t0 = time.perf_counter()
        assert all(backend.verify_batch(reqs))
        e2e.append(time.perf_counter() - t0)

    # Stage split: scan (dispatch + block) vs pair (on the scan output),
    # chunked EXACTLY like verify_batch so the stage walls decompose the
    # same kernels the e2e numbers ran (an unchunked _scan_dev on
    # ROOFLINE_SHARES > CHUNK would compile and time a bucket production
    # never uses).
    chunks = [
        reqs[s : s + backend.CHUNK] for s in range(0, len(reqs), backend.CHUNK)
    ]
    scan_s, pair_s = [], []
    for _ in range(reps):
        t0 = time.perf_counter()
        parts = [backend._scan_dev(c) for c in chunks]
        _block(parts)
        scan_s.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        ok = bool(backend._check_parts(parts))
        pair_s.append(time.perf_counter() - t0)
        assert ok

    # Cost analysis on the compiled kernels for these buckets, lowered
    # from the exact production inputs (_scan_prep is the same host prep
    # _scan_dev dispatches with).  Skipped BY DEFAULT on the relay-backed
    # TPU platform (_skip_cost notes); ROOFLINE_SKIP_COST overrides in
    # either direction.
    costs = {}
    skip_reason = _skip_cost()
    if skip_reason is not None:
        costs["skipped"] = True
        costs["skip_reason"] = skip_reason
    else:
        try:
            buckets, args = backend._scan_prep(reqs[: backend.CHUNK])
            costs["scan_bucket"] = list(buckets)
            costs["scan"] = _cost(tb._scan_kernel(*buckets), *args)
            part = backend._scan_dev(reqs[: backend.CHUNK])
            npairs = int(part[1][3].shape[0])
            costs["pair_bucket"] = tb._pairs_bucket(npairs)
            costs["pair"] = _cost(tb._pair_kernel(npairs), part[1], part[2])
        except Exception as e:
            costs["error"] = f"{type(e).__name__}: {e}"[:200]

    out = {
        "config": "flush_roofline",
        "shares": n_shares,
        "chunk": backend.CHUNK,
        "device": jax.devices()[0].platform,
        "warm_first_call_s": round(warm_s, 2),
        "e2e_s": [round(x, 3) for x in e2e],
        "scan_stage_s": [round(x, 3) for x in scan_s],
        "pair_stage_s": [round(x, 3) for x in pair_s],
        "verifies_per_sec_best": round(n_shares / min(e2e), 1),
        "cost_analysis": costs,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
