"""Mid device tier: a ~10-minute warm-cache slice of the heavy tests.

Round-4 VERDICT missing #5 / next-round #7: the full device tier costs
~45 min warm on this 1-core box (execution-bound pairing products) and
the smoke tier skips ALL eight heavy tests — so a time-boxed round could
regress the pairing/flush kernels without noticing.  This tier runs the
three heavy tests that cover exactly the graphs the kernel rounds keep
rewriting, on their smallest shape buckets:

* ``test_pairing_product_vs_oracle`` — Miller loop + final exp vs the
  pure-Python oracle (curve.py / pairing.py / fq.py changes all land
  here first),
* ``test_tpu_backend_matches_batched_backend`` — the production flush
  (RLC scans + endo subgroup checks + two-stage scan/pair split) against
  the host RLC backend,
* ``test_tpu_backend_sharded_flush_matches`` — the same flush dp-sharded
  over the virtual 8-device mesh, including a bad share (bisection).

Writes ``DEVICE_TIER_r{TAG}.json`` at the repo root: per-test pass/fail
plus wall time.  Usage (warm ``.jax_cache/`` assumed — a cold run adds
one-time compiles):

    DEVICE_TIER_TAG=05 python benchmarks/device_tier.py
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MID_TESTS = [
    "test_pairing_product_vs_oracle",
    "test_tpu_backend_matches_batched_backend",
    "test_tpu_backend_sharded_flush_matches",
]


def main() -> None:
    tag = os.environ.get("DEVICE_TIER_TAG", "dev")
    out_path = os.path.join(ROOT, f"DEVICE_TIER_r{tag}.json")
    results = []
    t_all = time.monotonic()
    for name in MID_TESTS:
        t0 = time.monotonic()
        # Strip the smoke-tier gate from the child env: all three tests
        # are @heavy_compile, so an inherited HBBFT_TPU_CRYPTO_SMOKE=1
        # (the documented quick-loop setting) would make every child
        # skip-and-exit-0 — a false green from the very tool meant to
        # catch kernel regressions.  A "skipped" summary is a failure.
        child_env = {
            k: v for k, v in os.environ.items()
            if k != "HBBFT_TPU_CRYPTO_SMOKE"
        }
        try:
            proc = subprocess.run(
                [
                    sys.executable, "-m", "pytest",
                    os.path.join(ROOT, "tests", "test_tpu_crypto.py"),
                    "-q", "-k", name, "--no-header", "-p", "no:cacheprovider",
                ],
                cwd=ROOT,
                capture_output=True,
                text=True,
                env=child_env,
                timeout=int(
                    os.environ.get("DEVICE_TIER_STEP_TIMEOUT_S", "1800")
                ),
            )
            rc = proc.returncode
            tail = (proc.stdout or "").strip().splitlines()
            summary = tail[-1] if tail else ""
            if rc == 0 and ("skipped" in summary or "1 passed" not in summary):
                rc = 1
                summary = f"did not pass exactly one test: {summary}"
        except subprocess.TimeoutExpired:
            # A cold cache shows up as a compile stall blowing the step
            # timeout — that must be RECORDED in the artifact (it is the
            # very signal README's deploy step 3 looks for), not a
            # traceback with no JSON written.
            rc = -1
            summary = "timeout (cold cache? prewarm per README deployment)"
        wall = round(time.monotonic() - t0, 1)
        results.append(
            {
                "test": name,
                "passed": rc == 0,
                "wall_s": wall,
                "summary": summary,
            }
        )
        print(f"{name}: rc={rc} wall={wall}s", flush=True)
    payload = {
        "tier": "device-mid",
        "tag": tag,
        "all_passed": all(r["passed"] for r in results),
        "total_wall_s": round(time.monotonic() - t_all, 1),
        "results": results,
    }
    with open(out_path, "w") as fh:
        fh.write(json.dumps(payload, indent=1) + "\n")
    print(json.dumps(payload))
    sys.exit(0 if payload["all_passed"] else 1)


if __name__ == "__main__":
    main()
