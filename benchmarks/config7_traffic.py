"""Config 7: traffic plane — open-loop clients over the TCP cluster.

The first benchmark with a latency story: a seeded client fleet offers
a sustained open-loop load through per-node mempools (paced against
committed batches), and every transaction is clocked submit→commit, so
the JSON line carries p50/p99 end-to-end latency next to epochs/s and
committed txns/s — under clean links and under seeded WAN shapes
(latency+jitter, optionally loss) from ``wan_profile``.

One JSON line per (N, profile):

    BENCH_TRAFFIC_NS="4,8,16" BENCH_TRAFFIC_PROFILES="clean,wan" \
        python benchmarks/config7_traffic.py

Drive modes (BENCH_TRAFFIC_DRIVE):

* ``open`` (default) — wall-clock open-loop arrivals for
  BENCH_TRAFFIC_DURATION_S, then drain.  Throughput and latency
  percentiles are the honest served-system numbers; cross-arm batch
  digests are NOT comparable (pacing races the faster arm ahead).
* ``presubmit`` — the fleet's first BENCH_TRAFFIC_TXNS arrivals are
  admitted and released in full before start (config6 determinism
  recipe fed by the client fleet): ``batches_sha`` is comparable
  across ``BENCH_TRAFFIC_IMPL=python|native`` at one seed.  The
  latency columns in this mode measure commit order, not
  client-visible latency — don't quote them.

Profiles: ``clean`` (no injector), ``wan`` (30 ms base + exp jitter on
every link), ``wan-lossy`` (the same + loss/dup on EVERY link — erodes
liveness by design, see faults.py), and ``faulty`` (WAN everywhere,
loss/dup only on ONE node's links — inside the f-tolerance envelope;
clients are homed on the survivors, so the run measures the cluster
serving traffic while carrying a degraded member).

Flight recorder (round 12): BENCH_TRACE=<dir> writes the merged Chrome
trace per line; BENCH_OBS_PORT serves live /metrics + /trace.json +
/healthz; every line carries epoch_lat_p50_s/p99 from the
EpochTracker-fed epoch.latency summary.

Env: BENCH_TRAFFIC_NS (default "4,8,16"), BENCH_TRAFFIC_PROFILES
(comma list of clean|wan|wan-lossy|faulty, default "clean,wan"),
BENCH_TRAFFIC_IMPL (python|native|mixed, default python),
BENCH_TRAFFIC_DRIVE (open|presubmit), BENCH_TRAFFIC_DURATION_S
(default 2.0), BENCH_TRAFFIC_TXNS (presubmit workload, default 32),
BENCH_TRAFFIC_CLIENTS_PER_NODE (default 2), BENCH_TRAFFIC_TPS
per client (default ``80/N^2``: QHB at the stock batch_size=8 commits
~N txns per epoch and Python-arm epochs slow ~quadratically with N on
this 1-core box, so a FIXED per-client rate drives big-N arms
hopelessly past capacity — the scaled default keeps every (N, arm)
inside a drainable envelope; set the env var for an absolute rate),
BENCH_TRAFFIC_WAN_SCALE (multiplies the profile's time constants,
default 1.0), BENCH_TRAFFIC_SEED (default 0),
BENCH_TRAFFIC_DEADLINE_S drain cap (default 120),
BENCH_TRAFFIC_METRICS=1 to embed the merged metrics snapshot.

Round 16: every line carries the analyzer's ``critical_path`` summary
(straggler/phase-share/skew/BA-rounds — docs/OBSERVABILITY.md
"Critical path & diagnosis") and ``trace_dropped`` (ring-overflow
honesty: nonzero means the trace-derived numbers are partial), via the
shared ``obs_extras`` plumbing.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hbbft_tpu.traffic import ClientFleet, TrafficDriver  # noqa: E402
from hbbft_tpu.transport import FaultInjector, LocalCluster  # noqa: E402
from hbbft_tpu.transport.faults import wan_profile  # noqa: E402
from hbbft_tpu.utils import serde  # noqa: E402

from config6_tcp_cluster import (  # noqa: E402
    obs_extras,
    preload_engine_serde,
    resolve_impl,
)


def build_injector(profile, n, seed, scale):
    """Injector (or None) + the id of the degraded node (or None)."""
    if profile == "clean":
        return None, None
    if profile == "faulty":
        lossy = wan_profile("wan-lossy", scale=scale)
        victim = n - 1
        links = {}
        for i in range(n):
            if i != victim:
                links[(i, victim)] = lossy
                links[(victim, i)] = lossy
        return (
            FaultInjector(
                seed=seed + 1000,
                default=wan_profile("wan", scale=scale),
                links=links,
            ),
            victim,
        )
    lf = wan_profile(profile, scale=scale)
    return FaultInjector(seed=seed + 1000, default=lf), None


def run_one(
    n: int,
    profile: str,
    *,
    impl: str,
    drive: str,
    duration_s: float,
    txns: int,
    clients_per_node: int,
    tps: float,
    wan_scale: float,
    seed: int,
    deadline_s: float,
) -> dict:
    injector, victim = build_injector(profile, n, seed, wan_scale)
    fleet = ClientFleet(clients_per_node * n, tps, seed=seed)
    rec = {
        "config": "config7_traffic",
        "nodes": n,
        "profile": profile,
        "node_impl": impl,
        "drive": drive,
        "seed": seed,
        "clients": clients_per_node * n,
        "offered_tps": round(fleet.offered_tps, 3),
        "wan_scale": wan_scale,
        "serde_native": serde._native_scan(serde.dumps(0)) is not None,
    }
    cluster = LocalCluster(
        n, seed=seed, node_impl=resolve_impl(impl, n), injector=injector
    )
    # faulty profile: home every client on a survivor — the degraded
    # node still participates in consensus (that's the point) but no
    # txn's commit observation depends on its lossy links staying live
    assign = None
    if victim is not None:
        rec["degraded_node"] = victim
        assign = lambda cid: cid % (n - 1)  # noqa: E731
    d = TrafficDriver(cluster, fleet, assign=assign)
    try:
        obs_port = os.environ.get("BENCH_OBS_PORT")
        if obs_port is not None:
            rec["obs_port"] = cluster.serve_obs(port=int(obs_port)).port
        if drive == "presubmit":
            ids = d.run_presubmit(txns)
            rec["presubmitted"] = len(ids)
            t0 = time.perf_counter()
            cluster.start()
            drained = d.drain(deadline_s)
            wall = time.perf_counter() - t0
            res = {
                "wall_s": wall,
                "arrived": d.arrived,
                "admitted": d.admitted,
                "committed": d.recorder.committed,
                "outstanding": d.outstanding(),
            }
            digest = hashlib.sha256()
            for b in cluster.batches(0):
                if not any(c for _, c in b.contributions):
                    continue  # trailing empty epochs differ across arms
                digest.update(serde.dumps((b.era, b.epoch, b.contributions)))
            rec["batches_sha"] = digest.hexdigest()[:16]
            rec["drained"] = drained
        else:
            cluster.start()
            res = d.run_open_loop(
                duration_s, drain_timeout_s=deadline_s
            )
            wall = res["wall_s"]
        # Epoch accounting now comes from the EpochTracker wired into
        # both node impls (round 12): min finished-count across nodes
        # replaces the ad-hoc batches() length math, and the commit
        # latency distribution rides in merged_metrics()'s
        # epoch.latency summary (obs_extras exports its p50/p99).
        epochs = min(cluster.batch_count(i) for i in cluster.nodes)
        hist = d.recorder.hist
        m = cluster.merged_metrics(fresh=True)
        rec.update(
            {
                "wall_s": round(wall, 2),
                "epochs_committed": epochs,
                "epochs_per_s": round(epochs / wall, 3) if wall else None,
                "arrived": res["arrived"],
                "admitted": res["admitted"],
                "committed_txns": res["committed"],
                "txns_per_s": round(res["committed"] / wall, 1)
                if wall
                else None,
                "outstanding": res["outstanding"],
                "lat_p50_s": round(hist.quantile(0.5), 4),
                "lat_p90_s": round(hist.quantile(0.9), 4),
                "lat_p99_s": round(hist.quantile(0.99), 4),
                "lat_max_s": round(hist.max if hist.count else 0.0, 4),
                "dup_suppressed": m.counters.get("traffic.dup_suppressed", 0),
                "mempool_overflow": m.counters.get(
                    "traffic.mempool_overflow", 0
                ),
                "frames_shaped": injector.stats.shaped if injector else 0,
                "frames_dropped": injector.stats.dropped if injector else 0,
                "protocol_faults": m.counters.get("cluster.protocol_faults", 0),
                "handler_errors": m.counters.get("cluster.handler_errors", 0),
                "complete": res["outstanding"] == 0,
            }
        )
        if os.environ.get("BENCH_TRAFFIC_METRICS"):
            rec["metrics"] = m.to_json()
        obs_extras(rec, cluster, f"config7_n{n}_{profile}_{impl}", m=m)
    finally:
        cluster.stop()
    return rec


def main() -> None:
    ns = [
        int(x)
        for x in os.environ.get("BENCH_TRAFFIC_NS", "4,8,16").split(",")
    ]
    profiles = os.environ.get("BENCH_TRAFFIC_PROFILES", "clean,wan").split(",")
    impl = os.environ.get("BENCH_TRAFFIC_IMPL", "python")
    drive = os.environ.get("BENCH_TRAFFIC_DRIVE", "open")
    duration = float(os.environ.get("BENCH_TRAFFIC_DURATION_S", "2.0"))
    txns = int(os.environ.get("BENCH_TRAFFIC_TXNS", "32"))
    cpn = int(os.environ.get("BENCH_TRAFFIC_CLIENTS_PER_NODE", "2"))
    tps_env = os.environ.get("BENCH_TRAFFIC_TPS")
    wan_scale = float(os.environ.get("BENCH_TRAFFIC_WAN_SCALE", "1.0"))
    seed = int(os.environ.get("BENCH_TRAFFIC_SEED", "0"))
    deadline = float(os.environ.get("BENCH_TRAFFIC_DEADLINE_S", "120"))
    preload_engine_serde()
    for n in ns:
        # scaled default rate: see the module docstring (fixed rates
        # drive big-N Python arms hopelessly past capacity)
        tps = float(tps_env) if tps_env else 80.0 / (n * n)
        for profile in profiles:
            rec = run_one(
                n,
                profile.strip(),
                impl=impl,
                drive=drive,
                duration_s=duration,
                txns=txns,
                clients_per_node=cpn,
                tps=tps,
                wan_scale=wan_scale,
                seed=seed,
                deadline_s=deadline,
            )
            print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
