"""Real-BLS era change at configurable N: wall + pairing/DKG split.

Round-4 VERDICT weak #2 / next-round #6: the decision to skip DKG
batching rests on an N=4 profile (75% pairing / 19% DKG), but the DKG
ack/row term grows ~N^3 while the pairing plane amortizes better with
batch size — so the split must be measured at larger N before the
conclusion can stand.  This runs the fused native stack (BLS votes +
real-BLS embedded DKG + era restart, flush_every=0) at BENCH_NODES and
prints one JSON line with the wall time and, under BENCH_PROFILE=1, the
cProfile share of the pairing plane (miller loop + final exp) vs the
DKG/group algebra (jac_mul + poly/commitment evaluation).  cProfile
inflates Python-frame-heavy code ~3x (CLAUDE.md round-2 lesson), so the
SHARES are the signal, never the absolute seconds.

    BENCH_NODES=16 BENCH_PROFILE=1 python benchmarks/bls_era_change.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hbbft_tpu import native_engine
from hbbft_tpu.crypto.bls import BLSSuite
from hbbft_tpu.protocols.dynamic_honey_badger import Change
from hbbft_tpu.protocols.queueing_honey_badger import Input


def run_era_change(n: int) -> dict:
    nat = native_engine.NativeQhbNet(
        n, seed=2, batch_size=max(8, n), num_faulty=0, session_id=b"bls-era",
        suite=BLSSuite(), flush_every=0,
    )
    keep = dict(nat.nodes[0].qhb.dhb.netinfo.public_key_map)
    keep.pop(n - 1)
    for nid in range(n):
        nat.send_input(nid, Input.change(Change.node_change(keep)))

    def done(e):
        return all(
            any(b.change.kind == "complete" for b in e.nodes[i].outputs)
            for i in e.correct_ids
        )

    t0 = time.perf_counter()
    rounds = 0
    for r in range(16):
        if done(nat):
            break
        rounds = r + 1
        for nid in range(n):
            nat.send_input(nid, Input.user(f"e{r}-{nid}"))
        want = len(nat.nodes[0].outputs) + 1
        nat.run_until(
            lambda e, w=want: all(
                len(e.nodes[i].outputs) >= w for i in e.correct_ids
            ),
            chunk=2000,
        )
    wall = time.perf_counter() - t0
    assert done(nat), "era change did not complete"
    new_pks = {
        nat.nodes[i].qhb.dhb.netinfo.public_key_set.to_bytes()
        for i in nat.correct_ids
    }
    assert len(new_pks) == 1, "nodes derived different master keys"
    out = {
        "config": "bls_native_era_change",
        "nodes": n,
        "era_change_wall_s": round(wall, 1),
        "epochs": rounds,
        "delivered_msgs": nat.delivered,
        "flush_stats": dict(nat.flush_stats),
    }
    nat.close()
    return out


# tottime buckets by source file (os.path basename under hbbft_tpu/):
# the pairing plane is the Batched backend's RLC verification math; the
# DKG algebra is the group/poly arithmetic SyncKeyGen drives; serde and
# the KEM are the other two named suspects from rounds 3-4.
_BUCKETS = {
    "pairing_plane": ("crypto/bls/pairing.py", "crypto/bls/fields.py"),
    "dkg_group_algebra": (
        "crypto/bls/curve.py", "crypto/poly.py",
        "protocols/sync_key_gen.py",
    ),
    "kem_keys": ("crypto/keys.py", "crypto/bls/suite.py"),
    "serde": ("utils/serde.py",),
}


def main() -> None:
    n = int(os.environ.get("BENCH_NODES", "16"))
    if os.environ.get("BENCH_PROFILE"):
        import cProfile
        import pstats

        prof = cProfile.Profile()
        prof.enable()
        out = run_era_change(n)
        prof.disable()
        stats = pstats.Stats(prof)
        total = 0.0
        buckets = {k: 0.0 for k in _BUCKETS}
        rows = []
        for (fname, _line, func), (cc, nc, tt, ct, callers) in stats.stats.items():
            total += tt
            norm = fname.replace("\\", "/")
            for bucket, paths in _BUCKETS.items():
                if any(norm.endswith(p) for p in paths):
                    buckets[bucket] += tt
                    break
            if tt > 0.5:
                rows.append((round(tt, 2), os.path.basename(fname), func))
        rows.sort(reverse=True)
        out["profile"] = {
            "tottime_total_s": round(total, 1),
            "shares": {
                k: round(v / total, 3) if total else 0
                for k, v in buckets.items()
            },
            "seconds": {k: round(v, 1) for k, v in buckets.items()},
            "top": rows[:20],
            "note": "cProfile shares, not absolutes (CLAUDE.md ~3x inflation)",
        }
    else:
        out = run_era_change(n)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
