"""BASELINE config 3: HoneyBadger 16-node network sim, 256-tx batches.

Uses the virtual-time simulation harness (examples/simulation.py) so the
numbers include the hardware-quality network model like the reference's
``examples/simulation.rs``.  Prints one JSON line.

Suite defaults to the insecure scalar suite (protocol-plane timing, like
running the reference with crypto hypothetically free); set
``BENCH_SUITE=bls`` for real threshold crypto (+``BENCH_BACKEND=tpu``
for the accelerated batch path).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from examples.simulation import build_network
from hbbft_tpu.protocols.queueing_honey_badger import Input


def main() -> None:
    args = argparse.Namespace(
        nodes=int(os.environ.get("BENCH_NODES", "16")),
        txns=int(os.environ.get("BENCH_TXNS", "256")),
        txn_size=16,
        batch_size=int(os.environ.get("BENCH_BATCH", "256")),
        lag_ms=100.0,
        bw_kbps=2000.0,
        cpu_factor=1.0,
        seed=0,
        suite=os.environ.get("BENCH_SUITE", "scalar"),
        backend=os.environ.get("BENCH_BACKEND", "batched"),
        flush_every=int(os.environ.get("BENCH_FLUSH", "1")),
    )
    import random

    net = build_network(args)
    rng = random.Random(7)
    txns = [rng.randbytes(args.txn_size) for _ in range(args.txns)]
    t0 = time.perf_counter()
    for i, txn in enumerate(txns):
        net.input(i % args.nodes, Input.user(txn))
    want = set(txns)
    net.run(lambda n: all(want <= set(node.committed) for node in n.nodes.values()))
    wall = time.perf_counter() - t0

    nodes = list(net.nodes.values())
    sim_end = max(max(n.epoch_done_at.values(), default=0.0) for n in nodes)
    epochs = len(set().union(*[set(n.epoch_done_at) for n in nodes]))
    print(
        json.dumps(
            {
                "config": "honey_badger_16node_256tx",
                "nodes": args.nodes,
                "suite": args.suite,
                "epochs": epochs,
                "sim_epoch_latency_s": round(sim_end / max(epochs, 1), 4),
                "sim_tx_per_s": round(args.txns / sim_end, 2) if sim_end else None,
                "wall_s": round(wall, 2),
                "msgs": sum(n.sent_msgs for n in nodes),
            }
        )
    )


if __name__ == "__main__":
    main()
