"""Config 9: crypto plane A/B — inline scalar vs the shared service.

The first benchmark where the TPU crypto work can serve a LIVE
cluster: per (N, crypto arm) it runs the traffic plane's open-loop
client fleet over a TCP cluster and prices the share-verification
path — epochs/s plus the submit→commit txn p50/p99 — so the
decrypt-after-order latency cost of threshold cryptography (PAPERS.md
arxiv 2407.12172) is a measured column, not an estimate.

One JSON line per (N, arm, impl):

    BENCH_CP_NS="4,8" BENCH_CP_ARMS="scalar,service-cpu" \
        python benchmarks/config9_crypto_plane.py

Arms:

* ``scalar`` — ``crypto="inline"``: native nodes verify in scalar C,
  Python nodes on their per-node BatchedBackend.  The baseline.
* ``service-cpu`` — ``crypto="service"``: every node's COIN/DECRYPT
  share checks flow through ONE shared CryptoPlaneService over a
  BatchedBackend (RLC pairing collapse amortized across nodes).  Runs
  on this box with no relay/XLA involvement.
* ``service-tpu`` — the same service over ``TpuBackend`` with the
  BLS12-381 suite (python node impl: the native wire grammar pins the
  scalar suite).  Gated behind ``BENCH_TPU=1``: needs the TPU relay
  (or a long-suffering CPU XLA compile — see CLAUDE.md cold-start
  budgets) and is NOT part of the mandatory matrix.
* ``service-proc`` — ``crypto="service-proc"`` (round 18): the same
  shared plane as ``service-cpu`` but in its OWN PROCESS behind the
  socket RPC boundary, so the column prices serialization + RPC on
  top of the amortization.  Both impls.
* ``inline-bls`` — ``crypto="inline"`` with the BLS12-381 suite
  (python impl: the native wire grammar pins the scalar suite).  The
  round-18 acceptance BASELINE: every node pays its own pairings.
* ``service-proc-bls`` — the BLS suite with every node's share checks
  routed to ONE service process (python impl).  Worker backend is
  ``batched`` by default; ``BENCH_TPU=1`` switches it to ``tpu``
  (worker spawned with the relay visible and a compile-scale RPC
  timeout) — the live-TPU-amortization headline arm.

Drive modes (BENCH_CP_DRIVE): ``open`` (default; honest latency
percentiles) or ``presubmit`` (deterministic workload — the line
carries ``batches_sha``, comparable across arms/impls at one seed; do
not quote presubmit latency).  ``BENCH_CP_KILL=1`` arms the mid-run
service-kill drill on the ``service-proc*`` arms: once every node has
committed a batch the service process takes a SIGKILL, and the line's
``kill_drill`` block records the fallback flip (the scripted version
of the tests/test_cryptoplane_proc.py drill — quote it only when
``complete`` is true and ``fallbacks`` > 0).

Env: BENCH_CP_NS (default "4"), BENCH_CP_ARMS (default
"scalar,service-cpu"; the round-18 acceptance pair is
"inline-bls,service-proc-bls" at N>=16 presubmit),
BENCH_CP_IMPLS (python|native list, default
"python,native"), BENCH_CP_DRIVE (open|presubmit, default open),
BENCH_CP_DURATION_S (default 2.0), BENCH_CP_TXNS (presubmit workload,
default 32), BENCH_CP_CLIENTS_PER_NODE (default 2), BENCH_CP_TPS (per
client; default 80/N^2 — config7's capacity-scaled rate),
BENCH_CP_WINDOW_S (service batching window, default 0.002),
BENCH_CP_SEED (default 0), BENCH_CP_DEADLINE_S (default 120),
BENCH_CP_METRICS=1 to embed the merged metrics snapshot.  BENCH_TRACE
/ BENCH_OBS_PORT work as in config6/7.

Round 16: every line carries the analyzer's ``critical_path`` summary
— on the service arms its ``flush`` block folds the ``cryptoplane``
track's per-epoch flush latency into the same object (the
decrypt-after-order latency price, arxiv 2407.12172) — plus
``trace_dropped`` (ring-overflow honesty), via ``obs_extras``.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hbbft_tpu.traffic import ClientFleet, TrafficDriver  # noqa: E402
from hbbft_tpu.transport import LocalCluster  # noqa: E402
from hbbft_tpu.utils import serde  # noqa: E402

from config6_tcp_cluster import obs_extras, preload_engine_serde  # noqa: E402


def build_cluster(n: int, arm: str, impl: str, seed: int, window_s: float):
    if arm == "scalar":
        return LocalCluster(n, seed=seed, node_impl=impl, crypto="inline")
    if arm == "service-cpu":
        return LocalCluster(
            n, seed=seed, node_impl=impl, crypto="service",
            service_kwargs=dict(window_s=window_s),
        )
    if arm == "service-tpu":
        # BLS suite + the TPU flush kernel behind the shared service;
        # python impl only (the native cluster wire grammar is pinned
        # to the scalar suite's share encoding).
        from hbbft_tpu.crypto.bls import BLSSuite
        from hbbft_tpu.crypto.tpu.backend import TpuBackend
        from hbbft_tpu.cryptoplane import CryptoPlaneService
        from hbbft_tpu.obs.trace import TraceBuffer

        suite = BLSSuite()
        service = CryptoPlaneService(
            TpuBackend(suite),
            window_s=window_s,
            trace=TraceBuffer("cryptoplane"),
        )
        return LocalCluster(
            n, seed=seed, node_impl="python", suite=suite,
            crypto="service", crypto_service=service,
            # compile-scale client timeout: a cold flush bucket is a
            # multi-minute XLA build — the 30 s default would silently
            # benchmark the CPU fallback under a service-tpu label
            service_kwargs=dict(timeout_s=3600.0),
        )
    if arm == "service-proc":
        return LocalCluster(
            n, seed=seed, node_impl=impl, crypto="service-proc",
            service_kwargs=dict(window_s=window_s),
        )
    if arm in ("inline-bls", "service-proc-bls"):
        from hbbft_tpu.crypto.bls import BLSSuite

        suite = BLSSuite()
        if arm == "inline-bls":
            return LocalCluster(
                n, seed=seed, node_impl="python", suite=suite,
                crypto="inline",
            )
        kw: dict = dict(window_s=window_s, backend="batched")
        if os.environ.get("BENCH_TPU") == "1":
            # compile-scale RPC timeout, relay visible in the worker: a
            # cold flush bucket is a multi-minute XLA build, and the 30 s
            # default would silently benchmark the CPU fallback under a
            # service label
            kw = dict(
                window_s=window_s, backend="tpu",
                timeout_s=3600.0, force_cpu_jax=False,
            )
        return LocalCluster(
            n, seed=seed, node_impl="python", suite=suite,
            crypto="service-proc", service_kwargs=kw,
        )
    raise ValueError(f"unknown arm {arm!r}")


def arm_kill_drill(cluster, kill_info: dict, deadline_s: float) -> None:
    """BENCH_CP_KILL=1: SIGKILL the service process once every node has
    committed a batch; the run keeps going on the clients' local
    fallbacks and the JSON line records the flip."""

    def _watch():
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            svc = cluster.crypto_service
            if svc is None or not getattr(svc, "alive", False):
                return
            counts = [cluster.batch_count(i) for i in cluster.nodes]
            if counts and min(counts) >= 1:
                try:
                    kill_info["stats_at_kill"] = {
                        k: v
                        for k, v in svc.stats()["counters"].items()
                        if k.startswith("crypto.")
                    }
                except Exception:
                    pass
                svc.kill()
                kill_info["killed"] = True
                kill_info["killed_at_epoch"] = min(counts)
                return
            time.sleep(0.05)

    threading.Thread(target=_watch, daemon=True).start()


def run_one(
    n: int, arm: str, impl: str, *, drive: str, duration_s: float,
    txns: int, clients_per_node: int, tps: float, window_s: float,
    seed: int, deadline_s: float,
) -> dict:
    fleet = ClientFleet(clients_per_node * n, tps, seed=seed)
    rec = {
        "config": "config9_crypto_plane",
        "nodes": n,
        "crypto_arm": arm,
        "node_impl": "python" if arm == "service-tpu" else impl,
        "drive": drive,
        "seed": seed,
        "clients": clients_per_node * n,
        "offered_tps": round(fleet.offered_tps, 3),
        "service_window_s": window_s if arm.startswith("service") else None,
        "serde_native": serde._native_scan(serde.dumps(0)) is not None,
    }
    cluster = build_cluster(n, arm, impl, seed, window_s)
    d = TrafficDriver(cluster, fleet)
    kill_info: dict = {}
    kill_armed = (
        os.environ.get("BENCH_CP_KILL") == "1"
        and arm.startswith("service-proc")
    )
    try:
        obs_port = os.environ.get("BENCH_OBS_PORT")
        if obs_port is not None:
            rec["obs_port"] = cluster.serve_obs(port=int(obs_port)).port
        if drive == "presubmit":
            ids = d.run_presubmit(txns)
            rec["presubmitted"] = len(ids)
            t0 = time.perf_counter()
            cluster.start()
            if kill_armed:
                arm_kill_drill(cluster, kill_info, deadline_s)
            drained = d.drain(deadline_s)
            wall = time.perf_counter() - t0
            res = {
                "arrived": d.arrived,
                "admitted": d.admitted,
                "committed": d.recorder.committed,
                "outstanding": d.outstanding(),
            }
            digest = hashlib.sha256()
            for b in cluster.batches(0):
                if not any(c for _, c in b.contributions):
                    continue  # trailing empty epochs differ across arms
                digest.update(serde.dumps((b.era, b.epoch, b.contributions)))
            rec["batches_sha"] = digest.hexdigest()[:16]
            rec["drained"] = drained
        else:
            cluster.start()
            if kill_armed:
                arm_kill_drill(cluster, kill_info, deadline_s)
            res = d.run_open_loop(duration_s, drain_timeout_s=deadline_s)
            wall = res["wall_s"]
        epochs = min(cluster.batch_count(i) for i in cluster.nodes)
        hist = d.recorder.hist
        m = cluster.merged_metrics(fresh=True)
        rec.update(
            {
                "wall_s": round(wall, 2),
                "epochs_committed": epochs,
                "epochs_per_s": round(epochs / wall, 5) if wall else None,
                "committed_txns": res["committed"],
                "txns_per_s": round(res["committed"] / wall, 1)
                if wall
                else None,
                "outstanding": res["outstanding"],
                "lat_p50_s": round(hist.quantile(0.5), 4),
                "lat_p99_s": round(hist.quantile(0.99), 4),
                "protocol_faults": m.counters.get("cluster.protocol_faults", 0),
                "handler_errors": m.counters.get("cluster.handler_errors", 0),
                "complete": res["outstanding"] == 0,
            }
        )
        # the crypto-plane columns: how the share checks were served
        rec["crypto"] = {
            "flushes": m.counters.get("crypto.flushes", 0),
            "requests": m.counters.get("crypto.requests", 0),
            "fallbacks": m.counters.get("crypto.fallbacks", 0),
        }
        sm = m.summaries.get("crypto.batch_size")
        if sm is not None:
            rec["crypto"]["batch_p50"] = round(sm.quantiles.get(0.5, 0.0), 1)
            rec["crypto"]["batch_p99"] = round(sm.quantiles.get(0.99, 0.0), 1)
        t = m.timers.get("crypto.flush")
        if t is not None:
            rec["crypto"]["flush_mean_s"] = round(t.mean_s, 5)
            rec["crypto"]["flush_max_s"] = round(t.max_s, 5)
        if arm.startswith("service-proc"):
            # RPC-boundary columns: client side from the merged node
            # metrics, service side from the worker's stats RPC (its
            # counters die with the process, so a killed service only
            # reports what the drill snapshotted)
            rec["crypto"]["rpc"] = {
                k: m.counters.get(f"crypto.rpc.{k}", 0)
                for k in (
                    "calls", "requests", "merged_requests", "merged_jobs",
                    "fallbacks", "fallback_requests", "connects",
                    "reconnects",
                )
            }
            rt = m.timers.get("crypto.rpc.round_trip")
            if rt is not None:
                rec["crypto"]["rpc"]["round_trip_mean_s"] = round(
                    rt.mean_s, 5
                )
            svc = cluster.crypto_service
            if svc is not None and getattr(svc, "alive", False):
                try:
                    rec["crypto"]["service"] = {
                        k: v
                        for k, v in svc.stats()["counters"].items()
                        if k.startswith("crypto.")
                    }
                except Exception:
                    pass
            if kill_armed:
                rec["kill_drill"] = {
                    "killed": kill_info.get("killed", False),
                    "killed_at_epoch": kill_info.get("killed_at_epoch"),
                    "epochs_after_kill": (
                        epochs - kill_info["killed_at_epoch"]
                        if "killed_at_epoch" in kill_info
                        else None
                    ),
                    "fallbacks": m.counters.get("crypto.rpc.fallbacks", 0),
                    "stats_at_kill": kill_info.get("stats_at_kill"),
                }
        if os.environ.get("BENCH_CP_METRICS"):
            rec["metrics"] = m.to_json()
        obs_extras(rec, cluster, f"config9_n{n}_{arm}_{impl}", m=m)
    finally:
        cluster.stop()
        # the service-tpu arm hands the cluster a pre-built service,
        # which the cluster does not own; stop it here (idempotent)
        if cluster.crypto_service is not None:
            cluster.crypto_service.stop()
    return rec


def main() -> None:
    ns = [int(x) for x in os.environ.get("BENCH_CP_NS", "4").split(",")]
    arms = os.environ.get("BENCH_CP_ARMS", "scalar,service-cpu").split(",")
    impls = os.environ.get("BENCH_CP_IMPLS", "python,native").split(",")
    drive = os.environ.get("BENCH_CP_DRIVE", "open")
    duration = float(os.environ.get("BENCH_CP_DURATION_S", "2.0"))
    txns = int(os.environ.get("BENCH_CP_TXNS", "32"))
    cpn = int(os.environ.get("BENCH_CP_CLIENTS_PER_NODE", "2"))
    tps_env = os.environ.get("BENCH_CP_TPS")
    window_s = float(os.environ.get("BENCH_CP_WINDOW_S", "0.002"))
    seed = int(os.environ.get("BENCH_CP_SEED", "0"))
    deadline = float(os.environ.get("BENCH_CP_DEADLINE_S", "120"))
    if "service-tpu" in arms and os.environ.get("BENCH_TPU") != "1":
        print(
            "# service-tpu arm skipped (set BENCH_TPU=1; needs the relay "
            "or a very warm .jax_cache)",
            file=sys.stderr,
        )
        arms = [a for a in arms if a != "service-tpu"]
    preload_engine_serde()
    for n in ns:
        tps = float(tps_env) if tps_env else 80.0 / (n * n)
        for arm in arms:
            if arm in ("service-tpu", "inline-bls", "service-proc-bls"):
                arm_impls = ["python"]  # BLS suite: python nodes only
            else:
                arm_impls = impls
            for impl in arm_impls:
                rec = run_one(
                    n, arm, impl, drive=drive, duration_s=duration,
                    txns=txns, clients_per_node=cpn, tps=tps,
                    window_s=window_s, seed=seed, deadline_s=deadline,
                )
                print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
