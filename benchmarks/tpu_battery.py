"""TPU first-contact battery (round-3 VERDICT item #2).

THE first action when the axon relay answers: capture every
hardware-blocked measurement in one serialized pass (the box has ONE
core — never overlap runs).  Each step is a subprocess with its own
timeout; every JSON line each step prints is echoed AND appended to
``BATTERY_r{N}.jsonl`` at the repo root, so a relay window of any
length yields a durable record of whatever completed.

Steps, in order (cheapest-signal-first so a short window still pays):

1. ``bench.py``            — the 10240-share headline flush + the
                             Pallas-Keccak single/multi-block probes
                             (per-size reruns: ``BENCH_SHARES=n``).
2. config5 firehose        — 10k-share verify batches, the BASELINE
                             config 5 scaling axis.
3. config3 native BLS,     — the fused stack on deployment routing:
   hybrid backend            HybridBackend sends the handful of big
                             deduped flushes (up to ~240 requests at
                             N=16) to the chip and the ~4-request
                             majority to the host — so the device rows
                             in the record come from the big flushes
                             only; a full-device run is
                             ``BENCH_BACKEND=tpu`` (budget one ~10-min
                             compile per flush shape bucket).

Run: ``python benchmarks/tpu_battery.py`` (optionally
``BATTERY_TAG=r03``).  A TPU probe gates the whole battery: if the
relay is down it emits one JSON line saying so and exits 0.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def probe_tpu(timeout_s: float = 60.0) -> tuple[bool, str]:
    """Subprocess probe (in-process jax.devices() hangs when the relay
    is down — see CLAUDE.md)."""
    code = "import jax; ds = jax.devices(); print(ds[0].platform)"
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            cwd=ROOT,
        )
    except subprocess.TimeoutExpired:
        return False, f"backend init timed out after {timeout_s:.0f}s (relay down?)"
    if r.returncode != 0:
        return False, (r.stderr or "probe failed").strip()[-300:]
    plat = (r.stdout or "").strip().splitlines()[-1] if r.stdout else ""
    if plat not in ("tpu", "axon"):
        return False, f"platform is {plat!r}, not tpu"
    return True, plat


def run_step(name: str, argv: list[str], env: dict, timeout_s: float, sink) -> None:
    t0 = time.monotonic()
    rec = {"step": name, "argv": argv}
    try:
        r = subprocess.run(
            argv, capture_output=True, text=True, timeout=timeout_s, cwd=ROOT,
            env={**os.environ, **env},
        )
        rec["rc"] = r.returncode
        rec["wall_s"] = round(time.monotonic() - t0, 1)
        lines = []
        for line in (r.stdout or "").splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                lines.append(json.loads(line))
            except json.JSONDecodeError:
                pass
        rec["results"] = lines
        if r.returncode != 0:
            rec["stderr_tail"] = (r.stderr or "")[-400:]
    except subprocess.TimeoutExpired as e:
        rec["rc"] = -1
        rec["wall_s"] = round(time.monotonic() - t0, 1)
        rec["error"] = f"timeout after {timeout_s:.0f}s"
        partial = (e.stdout or b"")
        if isinstance(partial, bytes):
            partial = partial.decode(errors="replace")
        rec["stdout_tail"] = partial[-400:]
    print(json.dumps(rec), flush=True)
    sink.write(json.dumps(rec) + "\n")
    sink.flush()


def main() -> None:
    tag = os.environ.get("BATTERY_TAG", "r05")
    out_path = os.path.join(ROOT, f"BATTERY_{tag}.jsonl")
    ok, note = probe_tpu()
    with open(out_path, "a") as sink:
        head = {
            "step": "probe",
            "tpu": ok,
            "note": note,
            "time": time.strftime("%Y-%m-%d %H:%M:%S"),
        }
        print(json.dumps(head), flush=True)
        sink.write(json.dumps(head) + "\n")
        sink.flush()
        if not ok:
            return
        # Timeouts re-budgeted after first contact (round 3): ONE flush
        # shape bucket costs ~10 min of XLA compile on this 1-core host
        # and a cold step can need two; a warm single-size bench.py run
        # is ~8 min wall (cache deserialization + relay latency).
        py = sys.executable
        # Round-4 kernels (static-endo + psi-split scans, run-length
        # Miller/final-exp) are NEW graphs: every flush bucket recompiles
        # once (~10 min/bucket on this host, persisted).  The sweep
        # sizes run smallest-first so the battery records the full
        # batch-scaling curve of the new kernel even if a later step
        # times out; 10240 reuses the 2048 + 4096 chunk buckets.
        run_step(
            "bench_flush_512", [py, "bench.py"],
            {"BENCH_SHARES": "512", "BENCH_DEADLINE_S": "2400"}, 2700, sink,
        )
        run_step(
            "bench_flush_2048", [py, "bench.py"],
            {"BENCH_SHARES": "2048", "BENCH_DEADLINE_S": "2400"}, 2700, sink,
        )
        run_step(
            "bench_flush_headline", [py, "bench.py"],
            {"BENCH_DEADLINE_S": "2400"}, 2700, sink,
        )
        run_step(
            "flush_roofline_2048", [py, "benchmarks/flush_roofline.py"],
            # Warm by construction: runs after the 2048 bench step
            # compiled its buckets.  Stage walls + cost_analysis are the
            # round-5 roofline record (VERDICT #1).
            {"ROOFLINE_SHARES": "2048"}, 2700, sink,
        )
        run_step(
            "config5_firehose", [py, "benchmarks/config5_firehose.py"],
            {}, 2700, sink,
        )
        run_step(
            "config3_native_bls_hybrid",
            [py, "benchmarks/config3_native_bls.py"],
            # Hybrid: tiny flushes (mean ~4 requests at N=16) stay on the
            # host; only device-worthy batches ride the chip — a pure
            # TpuBackend run would pay a fresh compile per small bucket.
            # FIRST-WINDOW CAVEAT (measured end of round 3): even the
            # hybrid's big-flush buckets cost several distinct ~10-min
            # compiles on a cold cache, so this step may spend its whole
            # budget compiling and time out on the FIRST battery run —
            # the compiles persist in .jax_cache/, and a second run
            # completes.  Expect the fused number on the rerun, not the
            # first pass.
            {"BENCH_BACKEND": "hybrid", "BENCH_TXNS": "64", "BENCH_BATCH": "64"},
            2700, sink,
        )


if __name__ == "__main__":
    main()
