"""Config 6: N-node TCP cluster epoch throughput over localhost.

The first benchmark that pays real socket costs: serde encode/decode of
every protocol message, frame plumbing, kernel round-trips, the ACK
resume layer, and thread scheduling of 2N threads on this 1-core box —
against the VirtualNet configs, the delta IS the transport tax.

One JSON line per N (like config1..5):

    BENCH_TCP_NS="4,8,16" BENCH_TCP_EPOCHS=5 python \
        benchmarks/config6_tcp_cluster.py

Round 9 A/B: ``BENCH_TCP_IMPL=native`` runs one C++ engine per node
(LocalCluster ``node_impl`` — the message-boundary wire API) against
the default ``python`` protocol-thread oracle.  Both arms pre-submit a
deterministic workload before start, so a native arm at seed s commits
byte-identical batches to the Python arm at seed s and the JSON's
``batches_sha`` can be compared across arms directly (the docs/
TRANSPORT.md oracle-mode recipe).  ``BENCH_TCP_DRIVE=paced`` restores
the round-8 wall-clock-paced feeder (throughput-trajectory continuity;
cross-arm digests are NOT comparable in that mode — pacing races).

Env: BENCH_TCP_NS (comma list, default "4,8,16"), BENCH_TCP_EPOCHS
(target epochs per N, default 5), BENCH_TCP_DEADLINE_S per N (default
300), BENCH_TCP_IMPL (python|native|mixed, default python; "mixed"
alternates arms per node id — one flight-recorder trace then carries
tracks from BOTH impls), BENCH_TCP_DRIVE (presubmit|paced, default
presubmit), BENCH_TCP_SEED (default 0), BENCH_TCP_METRICS=1 to embed
the merged metrics snapshot.

Flight recorder (round 12): BENCH_TRACE=<dir> writes the merged Chrome
trace (one file per line, path echoed in the JSON) — load it in
Perfetto / chrome://tracing; BENCH_OBS_PORT=<port> serves /metrics,
/trace.json and /healthz live during the run (port echoed too; 0 picks
a free one).  Native arms always carry their engine.cyc.<type> cycle
splits in the JSON line.

Round 14 — process-per-node arm: ``BENCH_PROC=1`` (or
``BENCH_TCP_IMPL=native_proc``) runs one cluster_worker OS process per
node (:class:`~hbbft_tpu.transport.proc_cluster.ProcCluster`, ephemeral
port-0 ready-line handshake, presubmit drive) instead of 2N threads in
this interpreter — the N=104 scale runs go through this arm.
``BENCH_PROC=1 BENCH_TCP_IMPL=python`` selects Python-oracle workers
(``python_proc``); ``BENCH_PROC_OBS=1`` gives every worker its own
scrape endpoints.  The JSON
line gains ``workers``/``ready_s``/``sha_identical`` (asserted across
ALL worker summaries, not just node 0) and ``min_epoch_contribs`` (the
non-empty-epochs check); ``batches_sha`` stays directly comparable with
the thread arms at one seed.  BENCH_TRACE also works here: each worker
dumps its trace file at exit and the parent merges them on the shared
wall clock.  The vectored-egress A/B for any arm is
``HBBFT_TPU_SENDMSG=0`` (buffered round-9 path) vs unset (sendmsg
gather egress) on the same build; every line records the live setting.

Round 20 — message coalescing: every line records the live ``coalesce``
arm (``HBBFT_TPU_COALESCE``; see docs/TRANSPORT.md "Message
coalescing") plus ``msgs_sent`` and ``msgs_per_frame`` (the coalescing
ratio — 1.0 on the per-message arm).  ``BENCH_TCP_COALESCE_AB=1`` runs
BOTH arms back to back per N on one build (thread arms only), printing
one line each and asserting the two ``batches_sha`` digests are
identical in presubmit drive — the batching-never-changes-semantics
pin, benchmarked.

Round 16: every line carries the analyzer's ``critical_path`` summary
(per-epoch critical path to commit, straggler attribution, phase share
of wall, cross-node skew, BA rounds — docs/OBSERVABILITY.md "Critical
path & diagnosis") and ``trace_dropped`` (ring-overflow honesty).  The
proc arm derives its ``critical_path`` from the parent-side trace
merge, so it needs BENCH_TRACE set.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The cluster is jax-free (scalar suite, CPU protocol stack): the
# import below must not drag the axon TPU plugin in, so keep the
# environment as the caller set it (CLAUDE.md bypass applies if jax
# ends up imported transitively).

from hbbft_tpu.protocols.queueing_honey_badger import Input  # noqa: E402
from hbbft_tpu.transport import LocalCluster  # noqa: E402
from hbbft_tpu.transport.transport import (  # noqa: E402
    _coalesce_default,
    _sendmsg_default,
)
from hbbft_tpu.utils import serde  # noqa: E402


def preload_engine_serde() -> bool:
    """Load the engine lib (build if needed) so ``serde.loads`` takes
    the C token-scan fast path even with Python nodes — round 8 ran
    this bench engine-free, paying the recursive decoder on every
    frame.  Returns whether the native scan is actually live."""
    try:
        from hbbft_tpu import native_engine

        if native_engine.get_lib() is None:
            return False
    except Exception:
        return False
    return serde._native_scan(serde.dumps(0)) is not None


def _engine_build_fields(n: int) -> dict:
    """Engine-build self-description for the JSON lines (round 15):
    SIMD dispatch arm + NodeSet width, so A/B rows name their arms per
    the CLAUDE.md clock-drift rules.  Uses the width THIS n selects
    (native nodes — in-process or proc-mode workers, which run the same
    loader — pick the -DHBE_WORDS build via _words_for), not the
    default build.  Empty when no engine lib loads (pure-Python arms
    still decode via it when present).  Round 17 adds the epoch-arena
    recycle knob (mirrors the engine's hbe_create env read — workers
    inherit the environment, so this names the arm for proc mode too)."""
    try:
        from hbbft_tpu import native_engine

        lib = native_engine.get_lib(native_engine._words_for(n))
        if lib is None:
            return {}
        return {
            "simd": native_engine.simd_mode(lib),
            "hbe_words": int(lib.hbe_words()),
            "arena_recycle": os.environ.get("HBBFT_TPU_ARENA", "1") != "0",
        }
    except Exception:
        return {}


def _sha3_plane_fields(n: int) -> dict:
    """Post-run sha3-plane counters (round 17).  Library-global since
    process start, so only the in-process (thread-mode) arms stamp
    them — the proc-mode parent never hashes, its counters would read
    ~0 while the workers did the work.  One cluster per benchmark
    process keeps them per-run in practice."""
    try:
        from hbbft_tpu import native_engine

        lib = native_engine.get_lib(native_engine._words_for(n))
        if lib is None:
            return {}
        st = native_engine.sha3_plane_stats(lib)
        return {"sha3": st} if st else {}
    except Exception:
        return {}


def resolve_impl(impl: str, n: int):
    """"mixed" = alternate node arms (even ids python, odd native), so
    one cluster/trace carries both impls."""
    if impl == "mixed":
        return {i: "native" if i % 2 else "python" for i in range(n)}
    return impl


def obs_extras(rec: dict, cluster, name: str, m=None) -> None:
    """Shared round-12 benchmark plumbing: engine cycle splits on every
    line, BENCH_TRACE=<dir> Chrome-trace dump, BENCH_OBS_PORT scrape
    endpoints (started by the caller right after cluster.start()).
    Pass the caller's merged-metrics snapshot via ``m`` so the JSON
    line's fields all come from ONE instant (and the merge+ring walk
    runs once per line).

    Round 16: every line also carries ``critical_path`` (the analyzer's
    per-run summary — straggler histograms, phase share of wall, skew,
    BA rounds, crypto-plane flush totals) and ``trace_dropped`` (total
    ring-overflow count; nonzero means the trace-derived numbers on
    this line are silently partial), with the per-node split when any
    ring actually dropped."""
    if m is None:
        m = cluster.merged_metrics(fresh=True)
    cyc = {
        k.split(".", 2)[2]: v
        for k, v in sorted(m.counters.items())
        if k.startswith("engine.cyc.")
    }
    if cyc:
        rec["engine_cyc"] = cyc
    sm = m.summaries.get("epoch.latency")
    if sm is not None:
        rec["epoch_lat_p50_s"] = round(sm.quantiles.get(0.5, 0.0), 4)
        rec["epoch_lat_p99_s"] = round(sm.quantiles.get(0.99, 0.0), 4)
    from hbbft_tpu.obs.analyze import critical_path, summarize_critical_paths

    rec["critical_path"] = summarize_critical_paths(
        critical_path(cluster.trace_events())
    )
    rec["trace_dropped"] = int(m.gauges.get("trace.dropped", 0))
    if rec["trace_dropped"]:
        rec["trace_dropped_by_node"] = {
            k.split(".")[1]: int(v)
            for k, v in sorted(m.gauges.items())
            if k.startswith("trace.") and k.endswith(".dropped")
            and k != "trace.dropped"
        }
    trace_dir = os.environ.get("BENCH_TRACE")
    if trace_dir:
        os.makedirs(trace_dir, exist_ok=True)
        path = os.path.join(trace_dir, f"{name}.trace.json")
        rec["trace_file"] = cluster.write_trace(path)


def run_n_proc(
    n: int, epochs: int, deadline_s: float, seed: int, impl: str = "native"
) -> dict:
    """One process-per-node measurement (``native_proc`` /
    ``python_proc``): spawn the fleet, deliver the address map, let the
    workers run the presubmit workload to ``epochs`` commits, and
    aggregate their summaries."""
    from hbbft_tpu.transport.proc_cluster import ProcCluster

    trace_dir = os.environ.get("BENCH_TRACE")
    t0 = time.perf_counter()
    cluster = ProcCluster(
        n,
        seed=seed,
        batch_size=8,
        impl=impl,
        epochs=epochs,
        drive="presubmit",
        timeout_s=deadline_s,
        obs=os.environ.get("BENCH_PROC_OBS") == "1",
        trace_dir=(
            os.path.join(trace_dir, f"config6_n{n}_proc") if trace_dir else None
        ),
    )
    rec = {
        "config": "config6_tcp_cluster",
        "nodes": n,
        "suite": "scalar",
        "transport": "tcp-localhost",
        "node_impl": f"{impl}_proc",
        "drive": "presubmit",
        "seed": seed,
        "workers": n,
        "threads_per_node": 3,  # selector loop + engine sweep + driver
        "vectored": _sendmsg_default(),
        # workers inherit the environment, so the env default names
        # the proc arm too (HBBFT_TPU_COALESCE)
        "coalesce": _coalesce_default(),
        "target_epochs": epochs,
    }
    rec.update(_engine_build_fields(n))
    try:
        cluster.start()
        rec["ready_s"] = round(time.perf_counter() - t0, 2)
        t0 = time.perf_counter()
        sums = cluster.join(timeout_s=deadline_s + 60.0)
        wall = time.perf_counter() - t0
        live = [s for s in sums.values() if s is not None]
        shas = sorted({s["batches_sha"] for s in live})
        committed = min((s["batches"] for s in live), default=0)
        msgs = sum(s["msgs_handled"] for s in live)
        rec.update(
            {
                "epochs_committed": committed,
                "wall_s": round(wall, 2),
                "epochs_per_s": round(committed / wall, 3) if wall else None,
                "msgs_handled": msgs,
                "msgs_per_s": round(msgs / wall, 1) if wall else None,
                "batches_sha": shas[0] if len(shas) == 1 else None,
                "sha_identical": len(shas) == 1 and len(live) == n,
                "min_epoch_contribs": min(
                    (min(s["epoch_contribs"], default=0) for s in live),
                    default=0,
                ),
                "handler_errors": sum(s["handler_errors"] for s in live),
                "protocol_faults": sum(s["faults"] for s in live),
                # ring-overflow honesty (round 16): summed from the
                # worker summaries — nonzero means the workers' trace
                # dumps (and the critical_path below) are partial
                "trace_dropped": sum(
                    s.get("trace_dropped", 0) for s in live
                ),
                "complete": all(
                    s is not None and s["done"] for s in sums.values()
                ),
            }
        )
    finally:
        cluster.stop()
    if trace_dir:
        merged = cluster.merged_chrome_trace()
        path = os.path.join(trace_dir, f"config6_n{n}_native_proc.trace.json")
        with open(path, "w") as fh:
            json.dump(merged, fh)
        rec["trace_file"] = path
        # critical_path over the parent-side merge: the same analyzer
        # the thread arms run over live rings (tools/analyze.py reads
        # the dumped file identically).
        from hbbft_tpu.obs.analyze import (
            critical_path,
            summarize_critical_paths,
            tracks_from_chrome,
        )

        rec["critical_path"] = summarize_critical_paths(
            critical_path(tracks_from_chrome(merged))
        )
    return rec


def run_n(
    n: int,
    epochs: int,
    deadline_s: float,
    impl: str,
    drive: str,
    seed: int,
    coalesce: bool = None,
) -> dict:
    t0 = time.perf_counter()
    kwargs = {}
    if coalesce is not None:  # the BENCH_TCP_COALESCE_AB dual-arm driver
        kwargs["transport_kwargs"] = {"coalesce": coalesce}
    cluster = LocalCluster(
        n, seed=seed, batch_size=8, node_impl=resolve_impl(impl, n), **kwargs
    )
    setup_s = time.perf_counter() - t0
    rec = {
        "config": "config6_tcp_cluster",
        "nodes": n,
        "suite": "scalar",
        "transport": "tcp-localhost",
        "node_impl": impl,
        "drive": drive,
        "seed": seed,
        "serde_native": serde._native_scan(serde.dumps(0)) is not None,
        "threads_per_node": 2,
        "vectored": _sendmsg_default(),
        "coalesce": _coalesce_default() if coalesce is None else coalesce,
        "target_epochs": epochs,
        "setup_s": round(setup_s, 3),
    }
    rec.update(_engine_build_fields(n))
    if drive == "presubmit":
        # Deterministic workload BEFORE start: every node sees the
        # identical txn queue in every arm, so the first `epochs`
        # batches are byte-identical across node_impls at one seed.
        for k in range(epochs + 4):
            for i in range(n):
                cluster.submit(i, Input.user(f"b-{k}-{i}"))
    t0 = time.perf_counter()
    try:
        cluster.start()
        obs_port = os.environ.get("BENCH_OBS_PORT")
        if obs_port is not None:
            rec["obs_port"] = cluster.serve_obs(port=int(obs_port)).port
        try:
            if drive == "presubmit":
                ok = cluster.wait(
                    lambda c: all(
                        len(c.batches(i)) >= epochs for i in range(n)
                    ),
                    deadline_s,
                )
                if not ok:
                    raise TimeoutError
            else:
                cluster.drive_to(range(n), epochs, timeout_s=deadline_s)
        except TimeoutError:
            pass  # report whatever committed within the deadline
        wall = time.perf_counter() - t0
        committed = min(len(cluster.batches(i)) for i in range(n))
        digest = hashlib.sha256()
        for b in cluster.batches(0)[:epochs]:
            digest.update(serde.dumps((b.era, b.epoch, b.contributions)))
        m = cluster.merged_metrics(fresh=True)
        frames = sum(
            st["frames_out"]
            for node in cluster.nodes.values()
            for st in node.transport.stats().values()
        )
        msgs_sent = sum(
            st["msgs_out"]
            for node in cluster.nodes.values()
            for st in node.transport.stats().values()
        )
        wire_bytes = sum(
            st["bytes_out"]
            for node in cluster.nodes.values()
            for st in node.transport.stats().values()
        )
        rec.update(
            {
                "epochs_committed": committed,
                "wall_s": round(wall, 2),
                "epochs_per_s": round(committed / wall, 3) if wall else None,
                "msgs_handled": m.counters.get("cluster.msgs_handled", 0),
                "msgs_per_s": round(
                    m.counters.get("cluster.msgs_handled", 0) / wall, 1
                ),
                "frames_sent": frames,
                "msgs_sent": msgs_sent,
                # the coalescing ratio: protocol messages per wire
                # frame (1.0 = the per-message arm, > 1 = batching)
                "msgs_per_frame": (
                    round(msgs_sent / frames, 2) if frames else None
                ),
                "wire_mb": round(wire_bytes / 1e6, 2),
                "batches_sha": digest.hexdigest()[:16],
                "protocol_faults": m.counters.get("cluster.protocol_faults", 0),
                "handler_errors": m.counters.get("cluster.handler_errors", 0),
                "complete": committed >= epochs,
            }
        )
        if os.environ.get("BENCH_TCP_METRICS"):
            rec["metrics"] = m.to_json()
        obs_extras(rec, cluster, f"config6_n{n}_{impl}", m=m)
        # Arena high-water marks ride the merged metrics already
        # (engine.cyc.arena via the slot-15 counter sync); the sha3
        # plane is library-global, so stamp it post-run here (thread
        # arms only — see _sha3_plane_fields).
        rec.update(_sha3_plane_fields(n))
    finally:
        cluster.stop()
    return rec


def main() -> None:
    ns = [int(x) for x in os.environ.get("BENCH_TCP_NS", "4,8,16").split(",")]
    epochs = int(os.environ.get("BENCH_TCP_EPOCHS", "5"))
    deadline = float(os.environ.get("BENCH_TCP_DEADLINE_S", "300"))
    impl = os.environ.get("BENCH_TCP_IMPL", "python")
    drive = os.environ.get("BENCH_TCP_DRIVE", "presubmit")
    seed = int(os.environ.get("BENCH_TCP_SEED", "0"))
    proc = (
        os.environ.get("BENCH_PROC") == "1" or impl.endswith("_proc")
    )
    coalesce_ab = os.environ.get("BENCH_TCP_COALESCE_AB") == "1" and not proc
    preload_engine_serde()
    for n in ns:
        if proc:
            # BENCH_TCP_IMPL still selects the worker implementation in
            # the proc arm: python → python_proc, anything else (the
            # default, native, native_proc) → native_proc.
            worker_impl = "python" if impl.startswith("python") else "native"
            rec = run_n_proc(n, epochs, deadline, seed, impl=worker_impl)
            print(json.dumps(rec), flush=True)
        elif coalesce_ab:
            # Dual-arm mode (round 20): both coalescing arms back to
            # back on one build, one line each.  Presubmit drive makes
            # batches_sha cross-arm comparable — a digest mismatch
            # means the coalescing layer changed protocol semantics,
            # so it is a hard failure, not a footnote.
            arms = []
            for arm in (False, True):
                rec = run_n(n, epochs, deadline, impl, drive, seed,
                            coalesce=arm)
                arms.append(rec)
                print(json.dumps(rec), flush=True)
            if drive == "presubmit" and all(a["complete"] for a in arms):
                assert arms[0]["batches_sha"] == arms[1]["batches_sha"], (
                    "coalescing arms committed different batches: "
                    f"{arms[0]['batches_sha']} vs {arms[1]['batches_sha']}"
                )
        else:
            rec = run_n(n, epochs, deadline, impl, drive, seed)
            print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
