"""Config 6: N-node TCP cluster epoch throughput over localhost.

The first benchmark that pays real socket costs: serde encode/decode of
every protocol message, frame plumbing, kernel round-trips, the ACK
resume layer, and thread scheduling of 2N threads on this 1-core box —
against the VirtualNet configs, the delta IS the transport tax.

One JSON line per N (like config1..5):

    BENCH_TCP_NS="4,8,16" BENCH_TCP_EPOCHS=5 python \
        benchmarks/config6_tcp_cluster.py

Env: BENCH_TCP_NS (comma list, default "4,8,16"), BENCH_TCP_EPOCHS
(target epochs per N, default 5), BENCH_TCP_DEADLINE_S per N (default
300), BENCH_TCP_METRICS=1 to embed the merged metrics snapshot.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The cluster is jax-free (scalar suite, CPU protocol stack): the
# import below must not drag the axon TPU plugin in, so keep the
# environment as the caller set it (CLAUDE.md bypass applies if jax
# ends up imported transitively).

from hbbft_tpu.transport import LocalCluster  # noqa: E402


def run_n(n: int, epochs: int, deadline_s: float) -> dict:
    t0 = time.perf_counter()
    cluster = LocalCluster(n, seed=0, batch_size=8)
    setup_s = time.perf_counter() - t0
    rec = {
        "config": "config6_tcp_cluster",
        "nodes": n,
        "suite": "scalar",
        "transport": "tcp-localhost",
        "threads_per_node": 2,
        "target_epochs": epochs,
        "setup_s": round(setup_s, 3),
    }
    t0 = time.perf_counter()
    try:
        cluster.start()
        try:
            cluster.drive_to(range(n), epochs, timeout_s=deadline_s)
        except TimeoutError:
            pass  # report whatever committed within the deadline
        wall = time.perf_counter() - t0
        committed = min(len(cluster.batches(i)) for i in range(n))
        m = cluster.merged_metrics()
        frames = sum(
            st["frames_out"]
            for node in cluster.nodes.values()
            for st in node.transport.stats().values()
        )
        wire_bytes = sum(
            st["bytes_out"]
            for node in cluster.nodes.values()
            for st in node.transport.stats().values()
        )
        rec.update(
            {
                "epochs_committed": committed,
                "wall_s": round(wall, 2),
                "epochs_per_s": round(committed / wall, 3) if wall else None,
                "msgs_handled": m.counters.get("cluster.msgs_handled", 0),
                "msgs_per_s": round(
                    m.counters.get("cluster.msgs_handled", 0) / wall, 1
                ),
                "frames_sent": frames,
                "wire_mb": round(wire_bytes / 1e6, 2),
                "protocol_faults": m.counters.get("cluster.protocol_faults", 0),
                "handler_errors": m.counters.get("cluster.handler_errors", 0),
                "complete": committed >= epochs,
            }
        )
        if os.environ.get("BENCH_TCP_METRICS"):
            rec["metrics"] = m.to_json()
    finally:
        cluster.stop()
    return rec


def main() -> None:
    ns = [int(x) for x in os.environ.get("BENCH_TCP_NS", "4,8,16").split(",")]
    epochs = int(os.environ.get("BENCH_TCP_EPOCHS", "5"))
    deadline = float(os.environ.get("BENCH_TCP_DEADLINE_S", "300"))
    for n in ns:
        print(json.dumps(run_n(n, epochs, deadline)), flush=True)


if __name__ == "__main__":
    main()
