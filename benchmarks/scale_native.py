"""Native-engine scale curve past the old 256-node cap (VERDICT #4).

Measures plain QHB epochs at large N on the engine (scalar suite,
GF(2^16) RBC codec for N > 255, per-width NodeSet builds).  A full
epoch's message count grows ~N^3 (N RBC instances x N^2 echo/ready
plus N^2 BA traffic), so wall time explodes with N; to keep runs
honest AND bounded, each N gets a full epoch if it fits the budget,
else a steady-state delivery-rate measurement over a fixed window with
the epoch time EXTRAPOLATED (flagged as such in the JSON).

Env: SCALE_NS (comma list, default "300,512"), SCALE_BUDGET_S per N
(default 5400), SCALE_WINDOW (rate-window deliveries, default 30M).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hbbft_tpu import native_engine
from hbbft_tpu.protocols.queueing_honey_badger import Input


def run_n(n: int, budget_s: float, window: int) -> dict:
    t0 = time.perf_counter()
    nat = native_engine.NativeQhbNet(n, seed=0, batch_size=8)
    setup_s = time.perf_counter() - t0
    for nid in nat.correct_ids:
        nat.send_input(nid, Input.user(f"tx{nid}"))

    def epoch_done(e) -> bool:
        return all(len(e.nodes[i].outputs) >= 1 for i in e.correct_ids)

    t0 = time.perf_counter()
    rec = {
        "config": "scale_native_epoch",
        "nodes": n,
        "suite": "scalar",
        "rbc_codec": "gf2^16" if n > 255 else "gf256",
        "setup_s": round(setup_s, 2),
    }
    chunk = 2_000_000
    while True:
        done = nat.run(chunk)
        elapsed = time.perf_counter() - t0
        if epoch_done(nat):
            rec.update(
                {
                    "epoch_wall_s": round(elapsed, 1),
                    "delivered": nat.delivered,
                    "msgs_per_s": round(nat.delivered / elapsed, 1),
                    "complete_epoch": True,
                }
            )
            break
        if done == 0:
            rec["error"] = "engine idle before epoch completion"
            break
        if elapsed > budget_s or nat.delivered >= window:
            # steady-state rate over the measured window; extrapolation
            # only, clearly flagged
            rec.update(
                {
                    "delivered": nat.delivered,
                    "window_wall_s": round(elapsed, 1),
                    "msgs_per_s": round(nat.delivered / elapsed, 1),
                    "complete_epoch": False,
                    "note": "budget/window reached before epoch completion; "
                    "msgs_per_s is steady-state over the window",
                }
            )
            break
    faults = sum(len(nat.faults(i)) for i in nat.correct_ids)
    rec["correct_node_faults"] = faults
    nat.close()
    return rec


def main() -> None:
    ns = [int(x) for x in os.environ.get("SCALE_NS", "300,512").split(",")]
    budget = float(os.environ.get("SCALE_BUDGET_S", "5400"))
    window = int(os.environ.get("SCALE_WINDOW", "30000000"))
    for n in ns:
        print(json.dumps(run_n(n, budget, window)), flush=True)


if __name__ == "__main__":
    main()
