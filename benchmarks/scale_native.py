"""Native-engine scale curve past the old 256-node cap (VERDICT #4).

Measures plain QHB epochs at large N on the engine (scalar suite,
GF(2^16) RBC codec for N > 255, per-width NodeSet builds).  A full
epoch's message count grows ~N^3 (N RBC instances x N^2 echo/ready
plus N^2 BA traffic), so wall time explodes with N; to keep runs
honest AND bounded, each N gets a full epoch if it fits the budget,
else a steady-state delivery-rate measurement over a fixed window with
the epoch time EXTRAPOLATED (flagged as such in the JSON).

Round 7: the JSON line carries per-message-type cyc/delivery
(``hbe_prof_cycles``/``hbe_prof_count``) plus the RLC group stats, so
the COIN/DECRYPT RLC A/B is one run per arm instead of hand-read
profiling slots:

    HBBFT_TPU_COIN_RLC=0 python benchmarks/scale_native.py   # old path
    HBBFT_TPU_COIN_RLC=1 python benchmarks/scale_native.py   # RLC arm

Compare ``cyc_per_delivery`` back-to-back on a quiet box (the counters
are rdtsc-based, but invariant-TSC cycles per instruction still swing
with the clock state — alternate the arms and compare pairs, CLAUDE.md
clock-drift rules).  The RLC arm runs the deferred scalar cadence at
``SCALE_FLUSH_EVERY`` (default 5000 — the measured N=300 optimum:
smaller windows pay per-flush overhead, larger ones thrash the
delivery caches and lag BA rounds; 0 = queue-dry measured WORSE at
N=300, BASELINE.md round 7).  The old path is eager-only, so the knob
is ignored there.

Round 15: the JSON line also carries the engine build's SIMD dispatch
arm (``simd``: ifma/scalar), the NodeSet width (``hbe_words``), and the
slot-14 combine-kernel stats, so the vectorized-field-plane A/B is two
self-describing runs:

    HBBFT_TPU_SIMD=0 python benchmarks/scale_native.py   # scalar arm
    HBBFT_TPU_SIMD=1 python benchmarks/scale_native.py   # IFMA arm

Adjudicate per the BASELINE round-8 format: alternate the arms
back-to-back on a quiet box, compare COIN/DECRYPT cyc/delivery and
``combine_kernel`` cycles/count, and control-correct with the untouched
BVAL slot.

Round 17: the line also carries the epoch-arena stats (``arena``:
high-water marks / resets / recycle knob) and the batched sha3-plane
counters (``sha3``), making the HBBFT_TPU_ARENA=0/1 recycling A/B two
self-describing runs; the slot-13 ``epoch_advance`` cyc/count is that
A/B's primary readout.

Env: SCALE_NS (comma list, default "300,512"), SCALE_BUDGET_S per N
(default 5400), SCALE_WINDOW (rate-window deliveries, default 30M),
SCALE_FLUSH_EVERY (RLC arm only; default 5000).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hbbft_tpu import native_engine
from hbbft_tpu.protocols.queueing_honey_badger import Input


def run_n(n: int, budget_s: float, window: int) -> dict:
    rlc_on = os.environ.get("HBBFT_TPU_COIN_RLC", "1") != "0"
    fe_env = os.environ.get("SCALE_FLUSH_EVERY")
    flush_every = int(fe_env) if fe_env is not None else (5000 if rlc_on else 1)
    t0 = time.perf_counter()
    nat = native_engine.NativeQhbNet(
        n, seed=0, batch_size=8,
        flush_every=flush_every if rlc_on else 1,
    )
    setup_s = time.perf_counter() - t0
    for nid in nat.correct_ids:
        nat.send_input(nid, Input.user(f"tx{nid}"))

    def epoch_done(e) -> bool:
        return all(len(e.nodes[i].outputs) >= 1 for i in e.correct_ids)

    t0 = time.perf_counter()
    rec = {
        "config": "scale_native_epoch",
        "nodes": n,
        "suite": "scalar",
        "rbc_codec": "gf2^16" if n > 255 else "gf256",
        "rlc": rlc_on,
        "flush_every": nat.flush_every,
        # Engine-build self-description (round 15): the SIMD dispatch arm
        # and NodeSet width, so A/B rows are self-describing per the
        # CLAUDE.md clock-drift rules.
        "simd": native_engine.simd_mode(nat.lib),
        "hbe_words": int(nat.lib.hbe_words()),
        "setup_s": round(setup_s, 2),
    }
    chunk = 2_000_000
    while True:
        done = nat.run(chunk)
        elapsed = time.perf_counter() - t0
        if epoch_done(nat):
            rec.update(
                {
                    "epoch_wall_s": round(elapsed, 1),
                    "delivered": nat.delivered,
                    "msgs_per_s": round(nat.delivered / elapsed, 1),
                    "complete_epoch": True,
                }
            )
            break
        if done == 0:
            rec["error"] = "engine idle before epoch completion"
            break
        if elapsed > budget_s or nat.delivered >= window:
            # steady-state rate over the measured window; extrapolation
            # only, clearly flagged
            rec.update(
                {
                    "delivered": nat.delivered,
                    "window_wall_s": round(elapsed, 1),
                    "msgs_per_s": round(nat.delivered / elapsed, 1),
                    "complete_epoch": False,
                    "note": "budget/window reached before epoch completion; "
                    "msgs_per_s is steady-state over the window",
                }
            )
            break
    faults = sum(len(nat.faults(i)) for i in nat.correct_ids)
    rec["correct_node_faults"] = faults
    # Per-message-type cyc/delivery (the RLC A/B readout).  The engine
    # folds deferred-flush verification + continuation cycles back into
    # COIN/DECRYPT and re-attributes replayed future messages and
    # epoch-boundary work to their own slots (engine_flush_pool /
    # Engine::replay_borrow), so the two arms' numbers compare the
    # actual share-path work.
    prof = nat.prof_stats()
    rec["cyc_per_delivery"] = {
        name: round(s["cycles"] / s["count"], 1)
        for name, s in prof.items()
        if name in native_engine.NativeQhbNet.MSG_TYPE_NAMES and s["count"]
    }
    rec["prof_counts"] = {
        name: prof[name]["count"]
        for name in native_engine.NativeQhbNet.MSG_TYPE_NAMES
        if prof[name]["count"]
    }
    rec["rlc_groups"] = prof["rlc_groups"]
    # The COIN/DECRYPT combine component (slot 14, round 15): the
    # direct readout for the HBBFT_TPU_SIMD A/B — cycles/combine on the
    # Lagrange-coefficients + combine-sum kernel.
    rec["combine_kernel"] = prof["combine_kernel"]
    # Epoch-arena + sha3-plane self-description (round 17): per-node
    # high-water marks / reset count / recycle knob for the
    # HBBFT_TPU_ARENA A/B, and the batched-hash counters (ifma_msgs > 0
    # iff the 8-lane arm actually ran).  sha3 counters are library-
    # global since process start — treat them as per-run only when one
    # engine ran in the process (true here).
    rec["arena"] = nat.arena_stats()
    rec["sha3"] = nat.sha3_stats()
    rec["epoch_advance"] = prof["epoch_advance"]
    if os.environ.get("SCALE_METRICS"):
        # Metrics-framework snapshot (counters/gauges; same shape the
        # TCP transport exports) — SCALE_METRICS=prom dumps Prometheus
        # text to stderr instead of embedding JSON.
        from hbbft_tpu.utils.metrics import Metrics

        m = Metrics()
        m.gauge("scale.nodes", n)
        m.count("scale.delivered", nat.delivered)
        for name, s in prof.items():
            if isinstance(s, dict) and "cycles" in s:
                m.count(f"engine.cycles.{name}", s["cycles"])
                m.count(f"engine.count.{name}", s["count"])
        if os.environ.get("SCALE_METRICS") == "prom":
            sys.stderr.write(m.prometheus_text())
        else:
            rec["metrics"] = m.to_json()
    nat.close()
    return rec


def main() -> None:
    ns = [int(x) for x in os.environ.get("SCALE_NS", "300,512").split(",")]
    budget = float(os.environ.get("SCALE_BUDGET_S", "5400"))
    window = int(os.environ.get("SCALE_WINDOW", "30000000"))
    for n in ns:
        print(json.dumps(run_n(n, budget, window)), flush=True)


if __name__ == "__main__":
    main()
