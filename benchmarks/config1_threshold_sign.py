"""BASELINE config 1: ThresholdSign 4-of-7, single message.

Metrics: share-verifies/sec (the suite's pairing-check rate) and
sign-to-combine latency over the virtual network with real BLS crypto.
Prints one JSON line.  Reference analog: upstream per-share verification
inside ``src/threshold_sign.rs`` (no published numbers; BASELINE.md).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import random

from hbbft_tpu.crypto.backend import EagerBackend, VerifyRequest
from hbbft_tpu.crypto.bls.suite import BLSSuite
from hbbft_tpu.crypto.keys import SecretKeySet
from hbbft_tpu.net import NetBuilder
from hbbft_tpu.protocols.threshold_sign import ThresholdSign


def main() -> None:
    suite = BLSSuite()
    rng = random.Random(1)
    # Share-verify rate: eager (per-pairing) path, the reference's model.
    sks = SecretKeySet.random(3, rng, suite)
    pks = sks.public_keys()
    msg = b"config1 document"
    n_checks = int(os.environ.get("BENCH_CHECKS", "24"))
    reqs = [
        VerifyRequest.sig_share(
            pks.public_key_share(i % 7), msg, sks.secret_key_share(i % 7).sign(msg)
        )
        for i in range(n_checks)
    ]
    eager = EagerBackend(suite)
    t0 = time.perf_counter()
    assert all(eager.verify_batch(reqs))
    dt = time.perf_counter() - t0
    verifies_per_sec = n_checks / dt

    # Sign-to-combine latency: 7-node net, threshold 3 (4-of-7).
    t0 = time.perf_counter()
    net = (
        NetBuilder(7, seed=2)
        .suite(suite)
        .backend(EagerBackend)
        .protocol(lambda ni, sink, rng_: ThresholdSign(ni, msg, sink))
        .build()
    )
    setup_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    net.broadcast_input(lambda nid: None)
    net.run_to_termination()
    latency_s = time.perf_counter() - t0
    sig = net.node(0).outputs[0]
    assert net.node(0).netinfo.public_key_set.verify_signature(msg, sig)

    print(
        json.dumps(
            {
                "config": "threshold_sign_4of7",
                "share_verifies_per_sec": round(verifies_per_sec, 2),
                "sign_to_combine_latency_s": round(latency_s, 4),
                "keygen_setup_s": round(setup_s, 3),
                "backend": "eager (per-pairing, reference-equivalent)",
            }
        )
    )


if __name__ == "__main__":
    main()
