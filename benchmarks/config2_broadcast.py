"""BASELINE config 2: Broadcast (RBC), 10 nodes, 1KB payload.

Metrics: delivery latency (wall time to all-node delivery over the
virtual net) and RS-encode + Merkle throughput for the data plane
(native C++ path when available).  Prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import random

from hbbft_tpu.net import NetBuilder
from hbbft_tpu.ops import native
from hbbft_tpu.ops.gf256 import ReedSolomon
from hbbft_tpu.ops.merkle import MerkleTree
from hbbft_tpu.protocols.broadcast import Broadcast


def main() -> None:
    payload = random.Random(0).randbytes(int(os.environ.get("BENCH_PAYLOAD", "1024")))

    t0 = time.perf_counter()
    net = (
        NetBuilder(10, seed=3)
        .protocol(lambda ni, sink, rng: Broadcast(ni, 0))
        .build()
    )
    net.send_input(0, payload)
    net.run_to_termination()
    deliver_s = time.perf_counter() - t0
    for nid in net.correct_ids:
        assert net.node(nid).outputs == [payload]

    # Data-plane throughput: RS(8-of-10) encode + Merkle over 1MB.
    big = random.Random(1).randbytes(1 << 20)
    k, n = 8, 10
    shard = len(big) // k
    shards = [big[i * shard : (i + 1) * shard] for i in range(k)]
    rs = ReedSolomon(k, n)
    t0 = time.perf_counter()
    full = rs.encode(shards)
    rs_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    MerkleTree(full)
    merkle_s = time.perf_counter() - t0

    print(
        json.dumps(
            {
                "config": "broadcast_10node_1kb",
                "deliver_latency_s": round(deliver_s, 4),
                "delivered_msgs": net.delivered,
                "rs_encode_mb_per_s": round(len(big) / 1e6 / rs_s, 2),
                "merkle_mb_per_s": round(len(big) * n / k / 1e6 / merkle_s, 2),
                "native_data_plane": native.available(),
            }
        )
    )


if __name__ == "__main__":
    main()
