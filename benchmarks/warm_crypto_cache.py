"""Warm the JAX compilation cache for the heavy crypto kernels.

The pairing / flush kernels are large XLA graphs: cold compiles cost
minutes each (round-3 audit on the virtual-CPU platform: pairing
product ~80 s, flush kernel ~7 min).  This script compiles the
canonical shape buckets ONCE, serially, with progress lines — run it
before a cold-cache `pytest tests/test_tpu_crypto.py` (or let any
prior full run populate `.jax_cache/`) and the heavy tier becomes
minutes-fast.

Usage (CPU tests):
    env PYTHONPATH= JAX_PLATFORMS=cpu python benchmarks/warm_crypto_cache.py
The cache location honors HBBFT_TPU_JAX_CACHE (default .jax_cache/).
"""

from __future__ import annotations

import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(msg: str) -> None:
    print(f"[warm {time.strftime('%H:%M:%S')}] {msg}", flush=True)


def main() -> None:
    from hbbft_tpu.utils.jaxcache import enable_cache

    enable_cache()

    from hbbft_tpu.crypto.backend import VerifyRequest
    from hbbft_tpu.crypto.bls.suite import BLSSuite
    from hbbft_tpu.crypto.keys import SecretKeySet
    from hbbft_tpu.crypto.tpu.backend import TpuBackend

    suite = BLSSuite()
    rng = random.Random(7)
    sks = SecretKeySet.random(1, rng, suite)
    pks = sks.public_keys()
    msg = b"warmup"
    backend = TpuBackend(suite)

    # The canonical test-tier bucket: (16, 16, 8) — a small mixed batch
    # (sig shares + ciphertext + decryption share) lands exactly here,
    # and every bisection sub-batch shares it thanks to the floors.
    t0 = time.time()
    reqs = []
    for i in range(3):
        share = sks.secret_key_share(i % 2).sign(msg)
        reqs.append(VerifyRequest.sig_share(pks.public_key_share(i % 2), msg, share))
    ct = pks.public_key().encrypt(b"warm-ct", rng)
    reqs.append(VerifyRequest.ciphertext(ct))
    reqs.append(
        VerifyRequest.dec_share(
            pks.public_key_share(0),
            ct,
            sks.secret_key_share(0).decryption_share(ct),
        )
    )
    ok = backend.verify_batch(reqs)
    assert all(ok), ok
    log(f"flush kernel bucket warmed in {time.time() - t0:.0f}s")

    # Bisection fallback path (compiles nothing new if the floors hold,
    # and pins that property).
    t0 = time.time()
    from hbbft_tpu.crypto.keys import SignatureShare

    bad = VerifyRequest.sig_share(
        pks.public_key_share(0), msg, SignatureShare(suite.g2_generator(), suite)
    )
    res = backend.verify_batch(reqs + [bad])
    assert res[:-1] == [True] * len(reqs) and res[-1] is False
    log(f"bisection path warmed in {time.time() - t0:.0f}s (shared bucket)")
    log("done")


if __name__ == "__main__":
    main()
