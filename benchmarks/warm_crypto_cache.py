"""Warm the JAX compilation cache for the heavy crypto kernels.

The pairing / flush kernels are large XLA graphs: cold compiles cost
minutes each (round-3 audit on the virtual-CPU platform: pairing
product ~80 s, flush kernel ~7 min).  This script compiles the
canonical shape buckets ONCE, serially, with progress lines — run it
before a cold-cache `pytest tests/test_tpu_crypto.py` (or let any
prior full run populate `.jax_cache/`) and the heavy tier becomes
minutes-fast.

Usage (CPU tests):
    env PYTHONPATH= JAX_PLATFORMS=cpu python benchmarks/warm_crypto_cache.py
The cache location honors HBBFT_TPU_JAX_CACHE (default .jax_cache/).
"""

from __future__ import annotations

import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(msg: str) -> None:
    print(f"[warm {time.strftime('%H:%M:%S')}] {msg}", flush=True)


def main() -> None:
    from hbbft_tpu.utils.jaxcache import enable_cache

    enable_cache()

    from hbbft_tpu.crypto.backend import VerifyRequest
    from hbbft_tpu.crypto.bls.suite import BLSSuite
    from hbbft_tpu.crypto.keys import SecretKeySet
    from hbbft_tpu.crypto.tpu.backend import TpuBackend

    suite = BLSSuite()
    rng = random.Random(7)
    sks = SecretKeySet.random(1, rng, suite)
    pks = sks.public_keys()
    msg = b"warmup"
    backend = TpuBackend(suite)

    # Warm each legs bucket the heavy tier touches (floor=2: buckets
    # 2/4/8 — the test-tier mixed batches land in nl=8, bisection
    # sub-batches in nl=4 and nl=2).  One distinct-leg batch per bucket.
    from hbbft_tpu.crypto.keys import SignatureShare

    def sig(i: int, m: bytes) -> VerifyRequest:
        return VerifyRequest.sig_share(
            pks.public_key_share(i % 2), m, sks.secret_key_share(i % 2).sign(m)
        )

    ct = pks.public_key().encrypt(b"warm-ct", rng)
    batches = {
        # nl=2: generator leg + one message-hash leg
        2: [sig(0, msg), sig(1, msg)],
        # nl=4 (3 legs): + a second distinct message
        4: [sig(0, msg), sig(1, b"warm-doc-2")],
        # nl=8 (5 legs): + ciphertext check + decryption share
        8: [
            sig(0, msg),
            sig(1, b"warm-doc-2"),
            VerifyRequest.ciphertext(ct),
            VerifyRequest.dec_share(
                pks.public_key_share(0),
                ct,
                sks.secret_key_share(0).decryption_share(ct),
            ),
        ],
    }
    for nl, reqs in sorted(batches.items()):
        t0 = time.time()
        ok = backend.verify_batch(reqs)
        assert all(ok), (nl, ok)
        log(f"flush kernel legs-bucket nl={nl} warmed in {time.time() - t0:.0f}s")

    # Bisection fallback: a bad share forces the aggregate to split; the
    # sub-batches reuse the buckets warmed above.
    t0 = time.time()
    bad = VerifyRequest.sig_share(
        pks.public_key_share(0), msg, SignatureShare(suite.g2_generator(), suite)
    )
    reqs8 = batches[8]
    res = backend.verify_batch(reqs8 + [bad])
    assert res[:-1] == [True] * len(reqs8) and res[-1] is False
    log(f"bisection path exercised in {time.time() - t0:.0f}s")

    # Single-chunk PAIR buckets (1 + nl = 3/5/9 pairs), compiled
    # directly: after a failed cross-chunk combine, the per-chunk
    # recheck in TpuBackend.verify_batch invokes _pair_kernel at
    # exactly these counts — a cache warmed only through combined
    # production buckets (WARM_SHARES) would eat a multi-minute cold
    # XLA compile on the FAILURE path, the worst possible moment on
    # this platform (ADVICE round 5).  Identity pairs compile the same
    # (n_pairs,)-shaped kernel the recheck uses and their product is 1.
    from hbbft_tpu.crypto.tpu import backend as tbackend
    from hbbft_tpu.crypto.tpu import curve as dcurve

    for b in (3, 5, 9):
        t0 = time.time()
        lhs = dcurve.identity(dcurve.G1_OPS, (b,))
        rhs = dcurve.identity(dcurve.G2_OPS, (b,))
        assert bool(tbackend._pair_kernel(b)(lhs, rhs)), b
        log(f"single-chunk pair bucket {b} pairs warmed in {time.time() - t0:.0f}s")

    # Production-size buckets (deployment prewarm, round-4 VERDICT #9):
    # WARM_SHARES=2048,10240 compiles the firehose-scale scan buckets +
    # the cross-chunk pair bucket so first real traffic never eats the
    # ~10-min-per-bucket compile wave.  NOTE the pair-stage bucket is
    # keyed by TOTAL pair count (chunks x (1+legs), padded to a multiple
    # of 8), so WARM_SHARES must list the flush sizes the deployment
    # actually issues — warming 10240 does NOT cover a 4096 flush's
    # 2-chunk pair bucket.  Signing n shares host-side costs ~12 ms
    # each, so reuse a handful of signatures across rows.
    shares_env = os.environ.get("WARM_SHARES", "")
    if shares_env:
        shares8 = [sks.secret_key_share(k % 2).sign(msg) for k in range(8)]
        for n_shares in [int(s) for s in shares_env.split(",") if s]:
            reqs = [
                VerifyRequest.sig_share(
                    pks.public_key_share(i % 2), msg, shares8[i % 8]
                )
                for i in range(n_shares)
            ]
            t0 = time.time()
            ok = backend.verify_batch(reqs)
            assert all(ok), n_shares
            log(
                f"production bucket {n_shares} shares "
                f"(CHUNK={backend.CHUNK}) warmed in {time.time() - t0:.0f}s"
            )

    # Crypto-plane service bucket (round 13): a cluster's shared
    # CryptoPlaneService merges several nodes' sig/dec/ct checks into
    # one device flush — the mixed-kind legs land in the SAME nl=8
    # bucket warmed above, but route here through the service worker
    # (config9's service-tpu arm) so the end-to-end path is exercised
    # once while the cache is being built.
    from hbbft_tpu.crypto.backend import BatchedBackend
    from hbbft_tpu.cryptoplane import CryptoPlaneService

    svc = CryptoPlaneService(backend, window_s=0.05)
    # Distinct CPU fallback (the worker owns the TpuBackend — a timed-
    # out client must never re-enter it concurrently) and a compile-
    # scale timeout: this flush COLD is a multi-minute XLA build.
    client = svc.client(BatchedBackend(suite), timeout_s=3600.0)
    t0 = time.time()
    ok = client.verify_batch(batches[8])
    assert all(ok)
    assert svc.metrics.counters.get("crypto.flushes", 0) == 1, (
        svc.metrics.counters
    )
    svc.stop()
    log(f"cryptoplane service flush warmed in {time.time() - t0:.0f}s")

    # Service-PROCESS arm (round 18): WARM_SERVICE_LEGS=1 spawns the
    # RPC worker with the TpuBackend and pushes the same mixed-kind
    # batch through the socket, so the WORKER's own .jax_cache entries
    # (config9's service-proc-bls BLS/TPU arm) get built now instead of
    # on first cluster traffic.  The worker inherits this process's
    # JAX_PLATFORMS/HBBFT_TPU_JAX_CACHE via force_cpu_jax=False — run
    # this under the same env the deployment will use.
    if os.environ.get("WARM_SERVICE_LEGS"):
        from hbbft_tpu.cryptoplane.proc_service import (
            RpcServiceClient,
            ServiceProcess,
        )

        t0 = time.time()
        with ServiceProcess(
            suite="bls", backend="tpu", force_cpu_jax=False,
            ready_timeout_s=600.0,
        ) as proc:
            rpc = RpcServiceClient(
                proc.addr, suite, BatchedBackend(suite), timeout_s=3600.0
            )
            ok = rpc.verify_batch(batches[8])
            assert all(ok)
            assert rpc.metrics.counters.get("crypto.rpc.fallbacks", 0) == 0, (
                rpc.metrics.counters
            )
            stats = proc.stats()["counters"]
            assert stats.get("crypto.flushes", 0) == 1, stats
            rpc.close()
        log(
            "service-process (rpc) flush warmed in "
            f"{time.time() - t0:.0f}s"
        )
    log("done")


if __name__ == "__main__":
    main()
