"""Config 8: the Byzantine price — traffic over a cluster with f
adversaries.

Config 7 priced WAN links; this config prices ADVERSARIES: the same
open-loop traffic plane (clients homed on honest nodes) over a cluster
whose last f nodes run live-socket Byzantine strategies
(hbbft_tpu.chaos), under clean and WAN link shapes, on both node
arms.  Every line embeds the safety/liveness oracle verdicts — an
epochs/s number over a cluster that silently diverged would be
worthless — plus the misbehavior plane's totals (strikes, bans,
rejected reconnects) so the defense's activity is visible next to the
attack's.

One JSON line per (N, profile):

    BENCH_CHAOS_NS="4,10" BENCH_CHAOS_PROFILES="clean,wan" \
        python benchmarks/config8_chaos.py
    BENCH_CHAOS_IMPL=native python benchmarks/config8_chaos.py

Strategy assignment (BENCH_CHAOS_STRATEGY): a single registry name
puts that strategy on every Byzantine node; ``mixed`` (default) cycles
corrupt-share / equivocate / flood across the f adversaries.

Latency caveat: percentiles here are honest open-loop submit→commit
numbers, but they include whatever the adversaries cost the honest
quorum — compare against the same (N, profile) line of config7 to
isolate the Byzantine price.

Flight recorder (round 12): BENCH_TRACE=<dir> writes the run's merged
Chrome trace (the Byzantine disruption window and the honest nodes'
recovery are visible per node track); BENCH_OBS_PORT serves /metrics,
/trace.json, /healthz live; BENCH_CHAOS_IMPL=mixed alternates node
arms so one trace carries both impls.

Env: BENCH_CHAOS_NS (default "4,10"), BENCH_CHAOS_PROFILES (comma list
of clean|wan|wan-lossy, default "clean,wan"), BENCH_CHAOS_IMPL
(python|native|mixed, default python), BENCH_CHAOS_STRATEGY (registry name
or "mixed"), BENCH_CHAOS_DURATION_S (default 2.0),
BENCH_CHAOS_CLIENTS_PER_NODE (default 2), BENCH_CHAOS_TPS per client
(default 80/N^2, the config7 scaling), BENCH_CHAOS_WAN_SCALE (default
1.0), BENCH_CHAOS_SEED (default 0), BENCH_CHAOS_DEADLINE_S drain cap
(default 120), BENCH_CHAOS_METRICS=1 embeds the metrics snapshot.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hbbft_tpu.chaos import ChaosOracle  # noqa: E402
from hbbft_tpu.traffic import ClientFleet, TrafficDriver  # noqa: E402
from hbbft_tpu.transport import FaultInjector, LocalCluster  # noqa: E402
from hbbft_tpu.transport.faults import wan_profile  # noqa: E402
from hbbft_tpu.utils import serde  # noqa: E402

from config6_tcp_cluster import (  # noqa: E402
    obs_extras,
    preload_engine_serde,
    resolve_impl,
)

_MIXED = ("corrupt-share", "equivocate", "flood")


def byzantine_map(n: int, f: int, strategy: str) -> dict:
    """The last f node ids run adversary arms (clients and oracle work
    over the honest prefix)."""
    ids = list(range(n - f, n))
    if strategy == "mixed":
        return {nid: _MIXED[k % len(_MIXED)] for k, nid in enumerate(ids)}
    return {nid: strategy for nid in ids}


def run_one(
    n: int,
    profile: str,
    *,
    impl: str,
    strategy: str,
    duration_s: float,
    clients_per_node: int,
    tps: float,
    wan_scale: float,
    seed: int,
    deadline_s: float,
) -> dict:
    f = (n - 1) // 3
    byz = byzantine_map(n, f, strategy)
    injector = None
    if profile != "clean":
        injector = FaultInjector(
            seed=seed + 1000, default=wan_profile(profile, scale=wan_scale)
        )
    honest = n - f
    fleet = ClientFleet(clients_per_node * honest, tps, seed=seed)
    rec = {
        "config": "config8_chaos",
        "nodes": n,
        "num_byzantine": f,
        "byzantine": {str(k): v for k, v in sorted(byz.items())},
        "profile": profile,
        "node_impl": impl,
        "seed": seed,
        "clients": clients_per_node * honest,
        "offered_tps": round(fleet.offered_tps, 3),
        "wan_scale": wan_scale,
        "serde_native": serde._native_scan(serde.dumps(0)) is not None,
    }
    cluster = LocalCluster(
        n,
        seed=seed,
        node_impl=resolve_impl(impl, n),
        injector=injector,
        byzantine=byz,
    )
    # home every client on an honest node: the adversaries still sit in
    # consensus (that is the point), but no commit observation depends
    # on a Byzantine mempool
    d = TrafficDriver(cluster, fleet, assign=lambda cid: cid % honest)
    oracle = ChaosOracle(cluster, driver=d)
    try:
        cluster.start()
        obs_port = os.environ.get("BENCH_OBS_PORT")
        if obs_port is not None:
            rec["obs_port"] = cluster.serve_obs(port=int(obs_port)).port
        res = d.run_open_loop(duration_s, drain_timeout_s=deadline_s)
        wall = res["wall_s"]
        epochs = min(cluster.batch_count(i) for i in oracle.honest_ids)
        hist = d.recorder.hist
        m = cluster.merged_metrics(fresh=True)
        verdict: dict = {}
        try:
            verdict["safety_prefix"] = oracle.assert_safety()
            verdict["safety"] = True
        except AssertionError as exc:
            verdict["safety"] = False
            verdict["safety_error"] = str(exc)[:200]
        try:
            verdict["byzantine_faults_named"] = oracle.assert_attribution()
            verdict["attribution"] = True
        except AssertionError as exc:
            verdict["attribution"] = False
            verdict["attribution_error"] = str(exc)[:200]
        try:
            verdict["exactly_once"] = bool(
                res["outstanding"] == 0 and oracle.assert_exactly_once() >= 0
            )
        except AssertionError as exc:
            verdict["exactly_once"] = False
            verdict["exactly_once_error"] = str(exc)[:200]
        rec.update(
            {
                "wall_s": round(wall, 2),
                "epochs_committed": epochs,
                "epochs_per_s": round(epochs / wall, 3) if wall else None,
                "arrived": res["arrived"],
                "admitted": res["admitted"],
                "committed_txns": res["committed"],
                "txns_per_s": round(res["committed"] / wall, 1)
                if wall
                else None,
                "outstanding": res["outstanding"],
                "lat_p50_s": round(hist.quantile(0.5), 4),
                "lat_p90_s": round(hist.quantile(0.9), 4),
                "lat_p99_s": round(hist.quantile(0.99), 4),
                "oracle": verdict,
                "chaos": {
                    k: v
                    for k, v in sorted(m.counters.items())
                    if k.startswith("chaos.")
                },
                "peer_misbehavior": m.counters.get(
                    "transport.peer_misbehavior", 0
                ),
                "peer_bans": m.counters.get("transport.peer_bans", 0),
                "ban_rejects": m.counters.get("transport.ban_rejects", 0),
                "bad_payload": m.counters.get("cluster.bad_payload", 0),
                "protocol_faults": m.counters.get("cluster.protocol_faults", 0),
                "handler_errors": m.counters.get("cluster.handler_errors", 0),
                "frames_shaped": injector.stats.shaped if injector else 0,
                "complete": res["outstanding"] == 0,
            }
        )
        if os.environ.get("BENCH_CHAOS_METRICS"):
            rec["metrics"] = m.to_json()
        obs_extras(rec, cluster, f"config8_n{n}_{profile}_{impl}", m=m)
    finally:
        cluster.stop()
    return rec


def main() -> None:
    ns = [
        int(x) for x in os.environ.get("BENCH_CHAOS_NS", "4,10").split(",")
    ]
    profiles = os.environ.get("BENCH_CHAOS_PROFILES", "clean,wan").split(",")
    impl = os.environ.get("BENCH_CHAOS_IMPL", "python")
    strategy = os.environ.get("BENCH_CHAOS_STRATEGY", "mixed")
    duration = float(os.environ.get("BENCH_CHAOS_DURATION_S", "2.0"))
    cpn = int(os.environ.get("BENCH_CHAOS_CLIENTS_PER_NODE", "2"))
    tps_env = os.environ.get("BENCH_CHAOS_TPS")
    wan_scale = float(os.environ.get("BENCH_CHAOS_WAN_SCALE", "1.0"))
    seed = int(os.environ.get("BENCH_CHAOS_SEED", "0"))
    deadline = float(os.environ.get("BENCH_CHAOS_DEADLINE_S", "120"))
    preload_engine_serde()
    for n in ns:
        tps = float(tps_env) if tps_env else 80.0 / (n * n)
        for profile in profiles:
            rec = run_one(
                n,
                profile.strip(),
                impl=impl,
                strategy=strategy,
                duration_s=duration,
                clients_per_node=cpn,
                tps=tps,
                wan_scale=wan_scale,
                seed=seed,
                deadline_s=deadline,
            )
            print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
